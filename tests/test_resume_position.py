"""Data-position checkpoint + mid-epoch resume (§5.4, VERDICT r4 #4).

Three layers:

- splitter: (seed, epoch)-deterministic shuffle permutations and
  arithmetic ``skip_records`` fast-forward on IndexedRecordIOSplitter
  (reference indexed_recordio_split.cc:12-41,221-233 can seek per
  record but its persistent-RNG shuffle is not resumable — documented
  divergence);
- Checkpointer: a ``meta`` dict stored under the same completeness
  guarantee as the tree (manifest for .d, pre-rename sidecar for .bin);
- end to end: a worker training on REAL rowrec data through
  ell_batches → StagingPipeline is killed mid-epoch (os._exit), a new
  process restores params + (epoch, records) and fast-forwards the
  pipeline — the resumed loss trajectory matches the uninterrupted
  run bit-for-bit.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ROWS, K, B = 256, 4, 32
N_EPOCHS = 2
CRASH_AT = 11  # global batch index: epoch 1, 3 batches in


def _write_indexed_rec(tmp_path, n=N_ROWS, k=K):
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream

    rng = np.random.default_rng(9)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=rng.integers(0, 2, n).astype(np.float32),
        index=rng.integers(0, 100, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.rec.idx")
    with FileStream(rec, "w") as data, FileStream(idx, "w") as index:
        w = IndexedRecordIOWriter(data, index)
        from dmlc_core_tpu.data.rowrec import encode_rows

        for payload in encode_rows(blk):
            w.write_record(payload)
    return rec, idx


def _epoch_order(rec, idx, epoch, skip=0):
    """Span-start order the splitter serves for a given epoch."""
    from dmlc_core_tpu.io import split as io_split

    s = io_split.IndexedRecordIOSplitter(
        rec, idx, batch_size=B, shuffle="batch", seed=3,
        epoch=epoch, skip_records=skip,
    )
    order = []
    while True:
        chunk = s.next_batch_ex(B)
        if chunk is None:
            break
        order.append(chunk[:64])  # head bytes identify the span
    consumed = s.records_consumed
    s.close()
    return order, consumed


def test_epoch_permutations_deterministic_and_distinct(tmp_path):
    rec, idx = _write_indexed_rec(tmp_path)
    e0, n0 = _epoch_order(rec, idx, 0)
    e0_again, _ = _epoch_order(rec, idx, 0)
    e1, _ = _epoch_order(rec, idx, 1)
    assert e0 == e0_again  # reproducible without replaying history
    assert e0 != e1  # still reshuffles across epochs
    assert n0 == N_ROWS
    # an in-place epoch rollover (before_first) matches a fresh
    # splitter constructed at that epoch
    from dmlc_core_tpu.io import split as io_split

    s = io_split.IndexedRecordIOSplitter(
        rec, idx, batch_size=B, shuffle="batch", seed=3
    )
    while s.next_batch_ex(B) is not None:
        pass
    s.before_first()  # epoch 1
    rolled = []
    while True:
        c = s.next_batch_ex(B)
        if c is None:
            break
        rolled.append(c[:64])
    s.close()
    assert rolled == e1


def test_skip_records_fast_forwards_to_same_tail(tmp_path):
    rec, idx = _write_indexed_rec(tmp_path)
    full, _ = _epoch_order(rec, idx, 1)
    tail, consumed = _epoch_order(rec, idx, 1, skip=3 * B)
    assert tail == full[3:]
    assert consumed == N_ROWS  # skip counts as consumed + the tail reads
    # misaligned skip in batch mode fails loudly
    from dmlc_core_tpu.utils.logging import Error as DmlcError

    with pytest.raises(DmlcError, match="span"):
        _epoch_order(rec, idx, 1, skip=3 * B + 7)


def test_tail_span_reads_last_so_batch_positions_resume(tmp_path):
    """With ntotal % batch_size != 0 the short remainder span must read
    LAST: otherwise a shuffle can place it early and batch-aligned
    checkpoint positions land mid-span (found by driving the criteo
    example with a 20000-row shard)."""
    n = N_ROWS - 10  # 246 rows: 7 full spans of 32 + a 22-record tail
    rec, idx = _write_indexed_rec(tmp_path, n=n)
    for epoch in range(3):
        full, consumed = _epoch_order(rec, idx, epoch)
        assert consumed == n
        # every full-span-multiple position is resumable...
        for k in (1, 3, 7):
            tail, _ = _epoch_order(rec, idx, epoch, skip=k * B)
            assert tail == full[k:], (epoch, k)
        # ...and the tail span is the final read (skipping everything
        # but the tail leaves exactly one span)
        last, _ = _epoch_order(rec, idx, epoch, skip=7 * B)
        assert len(last) == 1


def test_skip_records_sequential_and_record_modes(tmp_path):
    from dmlc_core_tpu.io import split as io_split

    rec, idx = _write_indexed_rec(tmp_path)
    # window=B makes batch positions window boundaries, so the same
    # skip is resumable in all three modes
    for mode in (False, "record", "window"):
        def order(skip):
            s = io_split.IndexedRecordIOSplitter(
                rec, idx, batch_size=B, shuffle=mode, seed=3,
                epoch=0, skip_records=skip, window=B,
            )
            out = []
            while True:
                c = s.next_batch_ex(B)
                if c is None:
                    break
                out.append(c)
            s.close()
            return out

        assert order(2 * B) == order(0)[2:], mode


def test_checkpointer_meta_roundtrip_single(tmp_path):
    from dmlc_core_tpu.checkpoint import Checkpointer

    ck = Checkpointer(str(tmp_path / "ck"), process_index=0)
    pos = {"epoch": 1, "records": 96}
    ck.save(4, {"w": np.ones(3, np.float32)}, meta=pos)
    assert ck.restore_meta() == pos
    assert ck.restore_meta(4) == pos
    # a meta-less same-step re-save clears the stale sidecar
    ck.save(4, {"w": np.ones(3, np.float32)})
    assert ck.restore_meta(4) is None
    # retention removes the sidecar with its checkpoint
    ck.save(5, {"w": np.ones(3, np.float32)}, meta={"epoch": 9})
    ck.save(6, {"w": np.ones(3, np.float32)})
    ck.save(7, {"w": np.ones(3, np.float32)})
    ck.save(8, {"w": np.ones(3, np.float32)})  # keep=3: 4,5 pruned
    names = set(os.listdir(tmp_path / "ck"))
    assert "ckpt-0000000005.meta.bin" not in names
    assert ck.restore_meta(8) is None


def test_checkpointer_meta_roundtrip_sharded(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.parallel import make_mesh

    mesh = make_mesh((8,), ("data",))
    w = jax.device_put(
        np.arange(8, dtype=np.float32), NamedSharding(mesh, P("data"))
    )
    ck = Checkpointer(str(tmp_path / "ck"), sharded=True)
    pos = {"epoch": 2, "records": 128}
    ck.save(3, {"w": w}, meta=pos)
    assert ck.restore_meta() == pos
    # async carries meta too
    h = ck.save_async(4, {"w": w}, meta={"epoch": 5})
    h.result(timeout=60)
    assert ck.restore_meta(4) == {"epoch": 5}


def test_sharded_resave_clears_stale_legacy_meta_sidecar(tmp_path):
    """A sharded re-save of a step that previously saved single-file
    WITH meta must remove the legacy .meta.bin alongside the .bin —
    otherwise a later restore_meta for a single-layout step could
    serve a position no sharded tree ever reached (ADVICE r5)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.parallel import make_mesh

    ckdir = tmp_path / "ck"
    single = Checkpointer(str(ckdir), process_index=0)
    single.save(7, {"w": np.ones(3, np.float32)}, meta={"records": 999})
    assert (ckdir / "ckpt-0000000007.meta.bin").exists()

    mesh = make_mesh((8,), ("data",))
    w = jax.device_put(
        np.arange(8, dtype=np.float32), NamedSharding(mesh, P("data"))
    )
    sharded = Checkpointer(str(ckdir), sharded=True)
    sharded.save(7, {"w": w}, meta={"records": 128})
    names = set(os.listdir(ckdir))
    assert "ckpt-0000000007.bin" not in names  # legacy tree gone
    assert "ckpt-0000000007.meta.bin" not in names  # and its sidecar
    assert sharded.restore_meta(7) == {"records": 128}
    # the async sharded path tears the same pair down
    single.save(8, {"w": np.ones(3, np.float32)}, meta={"records": 111})
    h = sharded.save_async(8, {"w": w}, meta={"records": 256})
    h.result(timeout=60)
    names = set(os.listdir(ckdir))
    assert "ckpt-0000000008.bin" not in names
    assert "ckpt-0000000008.meta.bin" not in names
    assert sharded.restore_meta(8) == {"records": 256}


WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from dmlc_core_tpu.checkpoint import Checkpointer
from dmlc_core_tpu.models import FactorizationMachine
from dmlc_core_tpu.staging import BatchSpec, StagingPipeline, ell_batches

B, K, N_EPOCHS, CRASH_AT = {B}, {K}, {n_epochs}, {crash_at}
REC, IDX, CKDIR, OUT, MODE = {rec!r}, {idx!r}, {ckdir!r}, {out!r}, {mode!r}

model = FactorizationMachine(100, 8)
params = model.init(jax.random.PRNGKey(0))
step_fn = jax.jit(lambda p, b: model.sgd_step(p, b, lr=0.1))
spec = BatchSpec(batch_size=B, layout="ell", max_nnz=K)
ck = Checkpointer(CKDIR)

def uri(epoch, skip=0):
    u = REC + f"?index={{IDX}}&shuffle=batch&batch_size={{B}}&seed=3"
    u += f"&epoch={{epoch}}"
    if skip:
        u += f"&skip_records={{skip}}"
    return u

losses = []
gstep = 0
start_epoch, skip = 0, 0
if MODE == "resume":
    gstep, params = ck.restore(template=params)
    pos = ck.restore_meta()
    assert pos is not None, "no data position in checkpoint"
    start_epoch, skip = pos["epoch"], pos["records"]

for epoch in range(start_epoch, N_EPOCHS):
    stream = ell_batches(uri(epoch, skip), spec)
    pipe = StagingPipeline(stream, depth=2)
    consumed = skip
    skip = 0
    for dev in pipe:
        params, loss = step_fn(params, dev)
        losses.append(float(loss))
        gstep += 1
        consumed += B
        ck.save(gstep, params,
                meta={{"epoch": epoch, "records": consumed}})
        if MODE == "crash" and gstep == CRASH_AT:
            # a real kill: no cleanup, no atexit, mid-epoch
            os._exit(17)
    stream.close()
    pipe.close()

with open(OUT, "w") as f:
    f.write(" ".join(np.float32(x).tobytes().hex() for x in losses))
"""


@pytest.mark.slow
@pytest.mark.jax
def test_midrun_kill_and_position_resume_bitexact(tmp_path):
    rec, idx = _write_indexed_rec(tmp_path)
    ckdir_s = str(tmp_path / "ck_straight")
    ckdir_c = str(tmp_path / "ck_crash")
    outs = {m: str(tmp_path / f"out_{m}") for m in
            ("straight", "crash", "resume")}

    def run(mode, ckdir, expect_rc=0):
        script = tmp_path / f"w_{mode}.py"
        script.write_text(textwrap.dedent(WORKER.format(
            repo=REPO, rec=rec, idx=idx, ckdir=ckdir, out=outs[mode],
            mode=mode, B=B, K=K, n_epochs=N_EPOCHS, crash_at=CRASH_AT,
        )))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.run(
            [sys.executable, str(script)], env=env,
            capture_output=True, text=True, timeout=300,
        )
        assert p.returncode == expect_rc, (mode, p.stdout, p.stderr)

    run("straight", ckdir_s)
    run("crash", ckdir_c, expect_rc=17)  # killed mid-epoch 1
    assert not os.path.exists(outs["crash"])  # really died mid-run
    run("resume", ckdir_c)

    straight = open(outs["straight"]).read().split()
    resumed = open(outs["resume"]).read().split()
    total = N_EPOCHS * (N_ROWS // B)
    assert len(straight) == total
    assert len(resumed) == total - CRASH_AT
    # bit-for-bit continuation through the kill point
    assert straight[CRASH_AT:] == resumed, (straight, resumed)
