"""The batched point-read path (io/lookup.py, docs/serving.md):
key resolution over the cached sidecar index, block reads through the
two-level decode context, frame-walk payload extraction, the serve
daemon, and the degradation matrix — results must be bit-identical
across {daemon on, daemon dead, L1-only} × {v1, zlib}.
"""

import os
import struct

import numpy as np
import pytest

from dmlc_core_tpu.io import codec as io_codec
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.blockcache import BlockCacheClient, BlockCacheDaemon
from dmlc_core_tpu.io.lookup import (
    LookupClient,
    LookupServer,
    RecordLookup,
    _extract_payloads,
)
from dmlc_core_tpu.io.recordio import KMAGIC, IndexedRecordIOWriter
from dmlc_core_tpu.io.stream import FileStream
from dmlc_core_tpu.tools import main as tools_main
from dmlc_core_tpu.utils.logging import Error

N_RECORDS = 400


def _payload(i: int) -> bytes:
    if i == 77:
        # an aligned magic word inside the payload forces the writer's
        # multi-part escape — the frame-walk's Python reassembly path
        return struct.pack("<I", KMAGIC) + b"chain" + struct.pack("<I", KMAGIC)
    return (b"%06d:" % i) + bytes([i % 251]) * (i % 53)


def _write_corpus(path, codec=None, n=N_RECORDS, key_fn=None, block_bytes=1024):
    with FileStream(path, "w") as f, FileStream(path + ".idx", "w") as fi:
        w = IndexedRecordIOWriter(f, fi, codec=codec, block_bytes=block_bytes)
        for i in range(n):
            key = i * 3 if key_fn is None else key_fn(i)
            w.write_record(_payload(i), key=key)
        w.flush()
    return path


def _l1_ctx():
    """A private L1-only decode context: no process-global cache, no
    daemon — every test measures its own reads."""
    return io_codec.DecodeContext(
        cache=io_codec.DecodedBlockCache(64 << 20), shared=None
    )


@pytest.fixture(params=["none", "zlib"])
def corpus(request, tmp_path):
    codec = None if request.param == "none" else request.param
    return _write_corpus(str(tmp_path / f"c_{request.param}.rec"), codec)


# -- core semantics -----------------------------------------------------------
def test_lookup_roundtrip_negatives_and_duplicates(corpus):
    h = RecordLookup(corpus, decode_ctx=_l1_ctx())
    try:
        keys = [0, 3, 231, 10**9, 231, -5, 3 * (N_RECORDS - 1)]
        vals = h.lookup(keys)
        assert vals[0] == _payload(0)
        assert vals[1] == _payload(1)
        assert vals[2] == _payload(77)  # the multi-part record
        assert vals[3] is None and vals[5] is None  # explicit negatives
        assert vals[4] == vals[2]  # duplicate query keys both answered
        assert vals[6] == _payload(N_RECORDS - 1)
        assert h.lookup([]) == []
        stats = h.io_stats()
        assert stats["negatives"] == 2
        assert stats["keys_resolved"] == 7
    finally:
        h.close()


def test_cross_codec_parity(tmp_path):
    """v1 and zlib shards answer identical bytes for identical keys —
    decoded blocks carry plain v1 frames, so the codec can never leak
    into lookup results."""
    v1 = _write_corpus(str(tmp_path / "v1.rec"), None)
    zl = _write_corpus(str(tmp_path / "zl.rec"), "zlib")
    raw = _write_corpus(str(tmp_path / "raw.rec"), "raw")
    keys = [0, 3, 231, 999, 3 * (N_RECORDS - 1), 42 * 3]
    answers = []
    for path in (v1, zl, raw):
        h = RecordLookup(path, decode_ctx=_l1_ctx())
        try:
            answers.append(h.lookup(keys))
        finally:
            h.close()
    assert answers[0] == answers[1] == answers[2]


def test_corrupt_block_is_checked_error_not_none(tmp_path):
    """A key that RESOLVES but whose block fails crc/decode must raise
    a checked Error — None is reserved for honest negative lookups."""
    path = _write_corpus(str(tmp_path / "corrupt.rec"), "zlib")
    with open(path, "r+b") as f:
        size = os.path.getsize(path)
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    try:
        with pytest.raises(Error):
            # every record: some key's block is the corrupted one
            h.lookup(list(range(0, 3 * N_RECORDS, 3)))
    finally:
        h.close()


def test_string_keys_resolve(tmp_path):
    path = str(tmp_path / "s.rec")
    with FileStream(path, "w") as f, FileStream(path + ".idx", "w") as fi:
        w = IndexedRecordIOWriter(f, fi, codec="zlib", block_bytes=512)
        for i in range(50):
            # the writer's key column is whatever the index stream got;
            # write a non-numeric sidecar by hand below
            w.write_record(b"val%03d" % i, key=i)
        w.flush()
    text = open(path + ".idx").read().split("\n")
    with open(path + ".idx", "w") as f:
        for line in text:
            if line:
                k, off = line.split("\t")
                f.write(f"user-{int(k):03d}\t{off}\n")
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    try:
        vals = h.lookup(["user-007", "user-000", "nope"])
        assert vals[0] == b"val007"
        assert vals[1] == b"val000"
        assert vals[2] is None
    finally:
        h.close()


def test_float_key_rejected_not_truncated(tmp_path):
    """A float key truncating to a neighboring id must raise, never
    return the wrong record (int(3.7) == 3 would)."""
    path = _write_corpus(str(tmp_path / "fk.rec"), None, n=20)
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    try:
        with pytest.raises(Error, match="must be integers"):
            h.lookup([3.7])
        assert h.lookup(["3"]) == [_payload(1)]  # exact wire form passes
    finally:
        h.close()


def test_string_index_rejects_unrepresentable_keys(tmp_path):
    """On a string-keyed index, bytes decode (the sidecar is text) and
    ints render exactly — but a float str()-ing into a never-matching
    key must raise, not masquerade as an honest negative."""
    path = str(tmp_path / "sk.rec")
    with FileStream(path, "w") as f, FileStream(path + ".idx", "w") as fi:
        w = IndexedRecordIOWriter(f, fi)
        for i in range(10):
            w.write_record(b"val%d" % i, key=i)
        w.flush()
    text = open(path + ".idx").read().splitlines()
    with open(path + ".idx", "w") as f:
        for line in text:
            k, off = line.split("\t")
            f.write(f"user{k}\t{off}\n")
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    try:
        assert h.lookup([b"user3", "user4"]) == [b"val3", b"val4"]
        with pytest.raises(Error, match="must be strings"):
            h.lookup([3.7])
    finally:
        h.close()


def test_oversized_key_batch_is_checked_error(tmp_path):
    """A key batch whose JSON header outgrows the control-frame cap is
    rejected at the SENDER with a checked Error naming the cap — not a
    dropped connection masquerading as a dead daemon."""
    path = _write_corpus(str(tmp_path / "big.rec"), None, n=20)
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    srv = LookupServer(h, port=0)
    try:
        c = LookupClient("127.0.0.1", srv.port)
        with pytest.raises(Error, match="frame cap|exceeds the"):
            c.lookup(list(range(10**9, 10**9 + 200_000)))
        # the connection survives (nothing was sent)
        assert c.lookup([0]) == [_payload(0)]
        c.close()
    finally:
        srv.close()
        h.close()


def test_duplicate_sidecar_key_fails_loudly(tmp_path):
    """Regression (ISSUE 13 satellite): a duplicated index key used to
    silently win by sort order — for point reads that is a wrong-record
    hazard, so the loader rejects it."""
    path = _write_corpus(
        str(tmp_path / "dup.rec"), "zlib", key_fn=lambda i: min(i, 7)
    )
    with pytest.raises(Error, match="duplicate key"):
        RecordLookup(path)


def test_odd_index_token_count_fails_loudly(tmp_path):
    path = _write_corpus(str(tmp_path / "odd.rec"), None)
    with open(path + ".idx", "a") as f:
        f.write("stray\n")
    with pytest.raises(Error, match="odd token count"):
        RecordLookup(path)


def test_epoch_reader_unaffected_by_key_retention(tmp_path):
    """The epoch path ignores keys entirely: an indexed drain over the
    same shard still yields every record in file order."""
    path = _write_corpus(str(tmp_path / "epoch.rec"), "zlib")
    sp = io_split.IndexedRecordIOSplitter(path, path + ".idx", 0, 1)
    try:
        got = [bytes(r) for r in iter(sp.next_record, None)]
    finally:
        sp.close()
    assert got == [_payload(i) for i in range(N_RECORDS)]


def test_extract_payloads_native_matches_fallback(tmp_path, monkeypatch):
    path = _write_corpus(str(tmp_path / "par.rec"), None, n=64)
    data = np.frombuffer(open(path, "rb").read(), dtype=np.uint8)
    # frame starts from the sidecar
    offs = np.asarray(
        [int(line.split()[1]) for line in open(path + ".idx")],
        dtype=np.int64,
    )
    sizes = np.concatenate((np.diff(offs), [len(data) - offs[-1]]))
    native_out = _extract_payloads(data, offs, sizes, "parity")
    from dmlc_core_tpu.data import native as native_mod

    monkeypatch.setattr(native_mod, "HAS_WALK_SPANS", False)
    fallback_out = _extract_payloads(data, offs, sizes, "parity")
    assert native_out == fallback_out
    assert native_out == [_payload(i) for i in range(64)]


def test_index_cache_eviction_counter(tmp_path, monkeypatch):
    """ISSUE 13 satellite: the parsed-index LRU is bytes-bounded and its
    evictions are a telemetry series, so a many-corpus serve daemon
    shows index churn instead of silent RSS growth."""
    from dmlc_core_tpu.telemetry import default_registry

    # a budget big enough for one parsed index, not two (each ~1.4 KB)
    monkeypatch.setattr(io_split, "_index_cache_budget", lambda: 2048)
    ctr = default_registry().counter("io.split.index_cache_evictions")
    before = ctr.value()
    for i in range(3):
        path = _write_corpus(str(tmp_path / f"m{i}.rec"), None, n=60)
        h = RecordLookup(path, decode_ctx=_l1_ctx())
        try:
            assert h.lookup([0]) == [_payload(0)]
        finally:
            h.close()
    assert ctr.value() > before
    with io_split._INDEX_CACHE_LOCK:
        assert len(io_split._INDEX_CACHE) <= 1


# -- degradation matrix -------------------------------------------------------
KEYSET = [0, 3, 231, 10**9, 3 * (N_RECORDS - 1), 300, 303, 306]


@pytest.mark.blockcache
def test_bit_identity_across_cache_tiers(tmp_path):
    """Acceptance: lookup results bit-identical across {daemon on,
    daemon dead, L1-only} × {v1, zlib} for the same key set."""
    for codec in (None, "zlib"):
        path = _write_corpus(
            str(tmp_path / f"mtx_{codec or 'v1'}.rec"), codec
        )
        answers = {}
        # L1-only
        h = RecordLookup(path, decode_ctx=_l1_ctx())
        answers["l1"] = h.lookup(KEYSET)
        h.close()
        # daemon on
        d = BlockCacheDaemon(
            str(tmp_path / f"bc_{codec or 'v1'}.sock"), max_bytes=64 << 20
        ).start()
        try:
            ctx = io_codec.DecodeContext(
                cache=io_codec.DecodedBlockCache(64 << 20),
                shared=BlockCacheClient(d.sock_path),
            )
            h = RecordLookup(path, decode_ctx=ctx)
            answers["daemon"] = h.lookup(KEYSET)
            # daemon DEAD mid-handle: a fresh L1 forces re-reads, the
            # dead client degrades to misses silently
            d.close()
            ctx2 = io_codec.DecodeContext(
                cache=io_codec.DecodedBlockCache(64 << 20),
                shared=BlockCacheClient(d.sock_path),
            )
            h2 = RecordLookup(path, decode_ctx=ctx2)
            answers["dead"] = h2.lookup(KEYSET)
            h.close()
            h2.close()
        finally:
            d.close()
        assert answers["l1"] == answers["daemon"] == answers["dead"]
        assert answers["l1"][3] is None  # the negative stays negative


@pytest.mark.blockcache
def test_warm_publishes_through_daemon(tmp_path):
    """warm() fetches+publishes the hot blocks; a SECOND process-shape
    (fresh L1, same daemon) then serves the whole key set with ZERO
    file reads — the shared tier did the work once."""
    path = _write_corpus(str(tmp_path / "warm.rec"), "zlib")
    d = BlockCacheDaemon(
        str(tmp_path / "warm.sock"), max_bytes=64 << 20
    ).start()
    try:
        ctx_a = io_codec.DecodeContext(
            cache=io_codec.DecodedBlockCache(64 << 20),
            shared=BlockCacheClient(d.sock_path),
        )
        h_a = RecordLookup(path, decode_ctx=ctx_a)
        warmed = h_a.warm(KEYSET)
        assert warmed > 0
        assert h_a.warm(KEYSET) == 0  # already resident
        h_a.close()
        ctx_b = io_codec.DecodeContext(
            cache=io_codec.DecodedBlockCache(64 << 20),
            shared=BlockCacheClient(d.sock_path),
        )
        h_b = RecordLookup(path, decode_ctx=ctx_b)
        vals = h_b.lookup(KEYSET)
        assert vals[0] == _payload(0)
        assert h_b.io_stats()["spans"] == 0  # zero reads: all from L2
        h_b.close()
    finally:
        d.close()


# -- serve daemon -------------------------------------------------------------
def test_serve_daemon_end_to_end(tmp_path):
    path = _write_corpus(str(tmp_path / "srv.rec"), "zlib")
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    srv = LookupServer(h, port=0)
    try:
        c = LookupClient("127.0.0.1", srv.port)
        assert c.ping()
        vals = c.lookup(KEYSET)
        assert vals[0] == _payload(0)
        assert vals[2] == _payload(77)
        assert vals[3] is None
        assert c.warm(max_blocks=4) >= 0
        # two clients at once: batches serialize on the handle lock
        c2 = LookupClient("127.0.0.1", srv.port)
        assert c2.lookup([0]) == [_payload(0)]
        st = c.stats()
        assert st["requests"] >= 4
        assert st["qps"] > 0
        assert "p99_ms" in st and "p50_ms" in st
        assert st["shard"]["records"] == N_RECORDS
        assert st["negatives"] >= 1
        c2.close()
        c.close()
    finally:
        srv.close()
        h.close()


def test_serve_daemon_reports_corrupt_as_error(tmp_path):
    path = _write_corpus(str(tmp_path / "srvbad.rec"), "zlib")
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        f.write(b"\xff\xff\xff\xff")
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    srv = LookupServer(h, port=0)
    try:
        c = LookupClient("127.0.0.1", srv.port)
        with pytest.raises(Error, match="refused"):
            c.lookup(list(range(0, 3 * N_RECORDS, 3)))
        # the connection survives a refused request
        assert c.ping()
        c.close()
    finally:
        srv.close()
        h.close()


def test_malformed_key_shapes_refused_not_iterated(tmp_path):
    """A scalar JSON string for keys would iterate char-by-char into
    VALID keys and answer wrong records; bools are ints to Python and
    would read key 0/1. Both must be checked refusals."""
    path = _write_corpus(str(tmp_path / "shape.rec"), None, n=20)
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    srv = LookupServer(h, port=0)
    try:
        with pytest.raises(Error, match="must be integers"):
            h.lookup([True])
        c = LookupClient("127.0.0.1", srv.port)
        with pytest.raises(Error, match="must be a JSON array"):
            c._request({"op": "lookup", "keys": "12"})
        with pytest.raises(Error, match="must be a JSON array"):
            c._request({"op": "warm", "keys": "12"})
        assert c.ping()  # the connection survives the refusals
        c.close()
    finally:
        srv.close()
        h.close()


def test_serve_daemon_unknown_op_refused(tmp_path):
    path = _write_corpus(str(tmp_path / "srvun.rec"), None, n=20)
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    srv = LookupServer(h, port=0)
    try:
        c = LookupClient("127.0.0.1", srv.port)
        with pytest.raises(Error, match="unknown op"):
            c._request({"op": "evil"})
        c.close()
    finally:
        srv.close()
        h.close()


def test_tools_info_reports_shard_geometry(tmp_path, capsys):
    import json

    path = _write_corpus(str(tmp_path / "info.rec"), "zlib")
    assert tools_main(["info", path]) == 0
    report = json.loads(capsys.readouterr().out)
    shard = report["shard"]
    assert shard["records"] == N_RECORDS
    assert shard["keys"] == N_RECORDS
    assert shard["compressed"] is True
    assert shard["codec"] == "zlib"
    assert shard["blocks"] > 1
    assert shard["block_bytes"]["min"] <= shard["block_bytes"]["max"]


def test_lookup_telemetry_series_tick(tmp_path):
    from dmlc_core_tpu.telemetry import default_registry

    reg = default_registry()
    b0 = reg.counter("io.lookup.batches").value()
    n0 = reg.counter("io.lookup.negatives").value()
    path = _write_corpus(str(tmp_path / "tel.rec"), "zlib", n=40)
    h = RecordLookup(path, decode_ctx=_l1_ctx())
    try:
        h.lookup([0, 10**9])
    finally:
        h.close()
    assert reg.counter("io.lookup.batches").value() == b0 + 1
    assert reg.counter("io.lookup.negatives").value() == n0 + 1
    snap = reg.snapshot()["histograms"]
    assert "io.lookup.batch_seconds" in snap


def test_lookup_wait_spans_have_flow_to_handler_spans(tmp_path):
    """ISSUE 14 acceptance (lookup half): every ``lookup_wait`` span on
    the client thread encloses a flow-start whose id matches a
    flow-finish inside a server-side ``dmlc:lookup_*`` handler span —
    Perfetto draws the causal arrow from the stall to the work."""
    from dmlc_core_tpu.telemetry import tracing

    tracing.reset()
    tracing.set_enabled(True)
    try:
        path = _write_corpus(str(tmp_path / "flow.rec"), "zlib")
        h = RecordLookup(path, decode_ctx=_l1_ctx())
        srv = LookupServer(h, port=0)
        try:
            c = LookupClient("127.0.0.1", srv.port)
            assert c.lookup([0]) == [_payload(0)]
            c.warm(max_blocks=2)
            c.stats()
            c.close()
        finally:
            srv.close()
            h.close()
        evs = tracing.to_chrome_trace()["traceEvents"]
        waits = [
            e for e in evs
            if e["ph"] == "X" and e["name"] == "dmlc:lookup_wait"
        ]
        assert waits, "no lookup_wait spans recorded"
        handlers = [
            e for e in evs
            if e["ph"] == "X" and e["name"].startswith("dmlc:lookup_")
            and e["name"] != "dmlc:lookup_wait"
        ]
        flows_s = {e["id"]: e for e in evs if e["ph"] == "s"}
        flows_f = {e["id"]: e for e in evs if e["ph"] == "f"}
        for w in waits:
            enclosed = [
                s for s in flows_s.values()
                if s["pid"] == w["pid"] and s["tid"] == w["tid"]
                and w["ts"] <= s["ts"] <= w["ts"] + w["dur"]
            ]
            assert enclosed, f"lookup_wait span at {w['ts']} has no flow"
            sid = enclosed[0]["id"]
            f = flows_f.get(sid)
            assert f is not None, "flow never landed server-side"
            host = next(
                (hs for hs in handlers
                 if hs["tid"] == f["tid"]
                 and hs["ts"] <= f["ts"] <= hs["ts"] + hs["dur"]),
                None,
            )
            assert host is not None, "flow-finish outside a handler span"
            # the handler kept the wire context in its args
            assert "tc" in host.get("args", {})
    finally:
        tracing.set_enabled(None)
        tracing.reset()
