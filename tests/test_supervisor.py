"""Cluster fault tolerance: the Supervisor (the YARN-AM capability).

Unit tier drives the retry/blacklist/abort state machine with fake
processes (reference handleFailure semantics,
ApplicationMaster.java:537-569); the end-to-end tier kills a real worker
mid-job under the local backend and watches it relaunch, reclaim its
rank through the tracker's jobid memo + recover path, and finish."""

import os
import sys

import pytest

from dmlc_core_tpu.tracker.supervisor import (
    JobAborted,
    Supervisor,
    default_max_attempt,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeProc:
    """Popen-alike whose exit code is scripted."""

    def __init__(self, returncode):
        self.returncode = returncode
        self.killed = False

    def poll(self):
        return self.returncode

    def kill(self):
        self.killed = True
        self.returncode = -9

    def wait(self):
        return self.returncode


def test_default_max_attempt(monkeypatch):
    monkeypatch.delenv("DMLC_MAX_ATTEMPT", raising=False)
    assert default_max_attempt() == 3
    monkeypatch.setenv("DMLC_MAX_ATTEMPT", "5")
    assert default_max_attempt() == 5
    monkeypatch.setenv("DMLC_MAX_ATTEMPT", "junk")
    assert default_max_attempt(4) == 4


def test_relaunch_until_success():
    """A task failing twice inside a 3-attempt budget is relaunched with
    an incrementing attempt index and the job completes."""
    log = []

    def launch(task_id, host, attempt):
        log.append((task_id, host, attempt))
        # task 1 fails on attempts 0 and 1, succeeds on 2
        if task_id == 1 and attempt < 2:
            return FakeProc(1)
        return FakeProc(0)

    sup = Supervisor(launch, hosts=["h0"], max_attempt=3, poll_interval=0,
                     relaunch_backoff=0)
    sup.run(2)
    assert sup.relaunches == 2
    assert sup.failures == {1: 2}
    assert [(h, a) for (t, h, a) in log if t == 1] == [
        ("h0", 0), ("h0", 1), ("h0", 2),
    ]


def test_multiple_failure_observers_all_fire():
    """on_task_failure is a LIST, not last-writer-wins: the shard
    service's lease reclaim and the collective engine's peer-death
    notification must coexist. Both fire per failure in registration
    order, and one raising does not rob the others (or the relaunch)."""
    calls = []

    def shardsvc_reclaim(task_id, host):
        calls.append(("reclaim", task_id, host))

    def collective_notify(task_id, host):
        calls.append(("notify", task_id, host))

    def bad_observer(task_id, host):
        calls.append(("bad", task_id, host))
        raise RuntimeError("observer bug")

    def launch(task_id, host, attempt):
        # task 0 fails once, then succeeds
        if task_id == 0 and attempt == 0:
            return FakeProc(1)
        return FakeProc(0)

    sup = Supervisor(launch, hosts=["h0"], max_attempt=3, poll_interval=0,
                     relaunch_backoff=0,
                     on_task_failure=[shardsvc_reclaim, bad_observer])
    sup.add_on_task_failure(collective_notify)
    sup.run(2)
    assert calls == [
        ("reclaim", 0, "h0"),
        ("bad", 0, "h0"),
        ("notify", 0, "h0"),
    ]
    assert sup.relaunches == 1  # the raising observer didn't abort it
    # a single callable still works (the pre-list signature)
    calls.clear()
    sup2 = Supervisor(launch, hosts=["h0"], max_attempt=3, poll_interval=0,
                      relaunch_backoff=0, on_task_failure=shardsvc_reclaim)
    assert sup2.on_task_failure == [shardsvc_reclaim]


def test_abort_past_budget_kills_survivors():
    """One more failure than max_attempt aborts the job and kills every
    still-running task (reference AM abort, ApplicationMaster.java:564)."""
    hang = FakeProc(None)  # never exits

    def launch(task_id, host, attempt):
        return FakeProc(1) if task_id == 0 else hang

    sup = Supervisor(launch, hosts=["h0"], max_attempt=2, poll_interval=0,
                     relaunch_backoff=0)
    with pytest.raises(JobAborted, match="task 0 failed 2 times"):
        sup.run(2)
    assert hang.killed
    assert isinstance(sup.error, JobAborted)


def test_blacklisted_host_moves_task():
    """Per-host failure accounting blacklists the bad host and re-places
    its task on a healthy one (reference node blacklist,
    ApplicationMaster.java:544-552)."""
    log = []

    def launch(task_id, host, attempt):
        log.append((task_id, host, attempt))
        return FakeProc(1 if host == "bad" else 0)

    sup = Supervisor(
        launch, hosts=["bad", "good"], max_attempt=3,
        host_fail_limit=1, poll_interval=0, relaunch_backoff=0,
    )
    sup.run(2)  # task 0 -> bad (fails, moves), task 1 -> good
    assert "bad" in sup.blacklist
    assert sup.placement[0] == "good"
    assert ("bad" not in {h for (_t, h, _a) in log[-1:]})


def test_pinned_placement_aborts_on_blacklist():
    """allow_replacement=False (tpu-pod: JAX process i must run on pod
    host i) turns a blacklisted host into a job abort."""

    def launch(task_id, host, attempt):
        return FakeProc(1 if task_id == 0 else None)

    sup = Supervisor(
        launch, hosts=["p0", "p1"], max_attempt=5,
        host_fail_limit=1, allow_replacement=False, poll_interval=0,
        relaunch_backoff=0,
    )
    with pytest.raises(JobAborted, match="cannot be re-placed"):
        sup.run(2)


def test_all_hosts_blacklisted_aborts():
    def launch(task_id, host, attempt):
        return FakeProc(1)

    sup = Supervisor(
        launch, hosts=["h0"], max_attempt=10,
        host_fail_limit=1, poll_interval=0, relaunch_backoff=0,
    )
    with pytest.raises(JobAborted, match="every host is blacklisted"):
        sup.run(1)


# -- relaunch backoff + host quarantine --------------------------------------


def test_relaunch_backoff_is_exponential():
    """Relaunches are spaced min(cap, base * 2**(k-1)) — a crash-looping
    task cannot hammer the cluster at poll speed."""
    import time as time_mod

    def launch(task_id, host, attempt):
        return FakeProc(1 if attempt < 3 else 0)

    sup = Supervisor(
        launch, hosts=["h0"], max_attempt=4, poll_interval=0,
        relaunch_backoff=0.05, backoff_cap=10.0, quarantine_secs=0,
    )
    t0 = time_mod.perf_counter()
    sup.run(1)
    elapsed = time_mod.perf_counter() - t0
    assert sup.backoffs == [0.05, 0.1, 0.2]
    assert elapsed >= 0.35, "relaunches were not actually spaced"


def test_relaunch_backoff_capped():
    def launch(task_id, host, attempt):
        return FakeProc(1 if attempt < 3 else 0)

    sup = Supervisor(
        launch, hosts=["h0"], max_attempt=4, poll_interval=0,
        relaunch_backoff=0.01, backoff_cap=0.015, quarantine_secs=0,
    )
    sup.run(1)
    assert sup.backoffs == [0.01, 0.015, 0.015]


def test_quarantined_host_not_retried_when_alternative_exists():
    """After a failure the host is quarantined: the relaunch moves to
    another healthy host instead of the immediate same-host retry —
    even though the failing host is NOT blacklisted."""
    log = []

    def launch(task_id, host, attempt):
        log.append((task_id, host, attempt))
        return FakeProc(1 if host == "h0" and attempt == 0 else 0)

    sup = Supervisor(
        launch, hosts=["h0", "h1"], max_attempt=3,
        host_fail_limit=10,  # far from blacklisting
        poll_interval=0, relaunch_backoff=0, quarantine_secs=30.0,
    )
    sup.run(1)
    assert "h0" not in sup.blacklist
    assert sup.quarantined.get("h0", 0) > 0
    assert [(h, a) for (_t, h, a) in log] == [("h0", 0), ("h1", 1)]


def test_quarantine_doubles_on_repeat_failures():
    """Repeated failures on one host grow its quarantine window
    exponentially — the 'host whose tasks die repeatedly' signal."""
    import time as time_mod

    def launch(task_id, host, attempt):
        return FakeProc(1 if attempt < 2 else 0)

    sup = Supervisor(
        launch, hosts=["h0"], max_attempt=3, poll_interval=0,
        relaunch_backoff=0, quarantine_secs=100.0,
    )
    releases = []
    orig = sup._quarantine

    def spy(host):
        orig(host)
        releases.append(sup.quarantined[host] - time_mod.monotonic())

    sup._quarantine = spy
    sup.run(1)
    assert len(releases) == 2
    assert releases[1] > releases[0] * 1.5  # doubled window


def test_sole_quarantined_host_still_used():
    """Liveness beats placement hygiene: with every healthy host
    quarantined, the relaunch proceeds on the previous host."""

    def launch(task_id, host, attempt):
        return FakeProc(1 if attempt == 0 else 0)

    sup = Supervisor(
        launch, hosts=["only"], max_attempt=3, poll_interval=0,
        relaunch_backoff=0, quarantine_secs=60.0,
    )
    sup.run(1)
    assert sup.placement[0] == "only"
    assert sup.relaunches == 1


# -- end to end over the local backend ---------------------------------------

CRASHY_WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.tracker.client import RabitWorker

out = {out!r}
task = os.environ["DMLC_TASK_ID"]
attempt = int(os.environ["DMLC_NUM_ATTEMPT"])

def wait_for(path, deadline=30.0):
    end = time.time() + deadline
    while not os.path.exists(path):
        if time.time() > end:
            raise SystemExit("timeout waiting for " + path)
        time.sleep(0.02)

w = RabitWorker()
rank = w.start()
with open(out + "task%s_attempt%d" % (task, attempt), "w") as f:
    f.write(str(rank))
if task == "1" and attempt == 0:
    # die mid-job, after rendezvous: the supervisor must relaunch us
    open(out + "crashed", "w").close()
    os._exit(7)
if task == "0":
    # stay alive through the peer's crash, then re-rendezvous so the
    # recovered worker can wire its links (rabit recover contract)
    wait_for(out + "crashed")
    w.close()
    w2 = RabitWorker()
    rank = w2.start(recover_rank=rank)
    w2.shutdown()
else:
    w.shutdown()
"""


def test_worker_killed_mid_job_relaunches_with_same_rank(tmp_path):
    """VERDICT r2 'done' criterion: kill a worker mid-job, see the
    supervisor relaunch it and the tracker re-issue the same rank."""
    out = str(tmp_path / "s_")
    script = tmp_path / "crashy.py"
    script.write_text(CRASHY_WORKER.format(repo=REPO, out=out))
    import importlib

    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main(
        ["--cluster", "local", "--num-workers", "2",
         "--local-num-attempt", "2",
         "--host-ip", "127.0.0.1", sys.executable, str(script)]
    )
    first = open(out + "task1_attempt0").read()
    second = open(out + "task1_attempt1").read()
    assert first == second, "relaunched worker got a different rank"
    assert os.path.exists(out + "task0_attempt0")


def test_job_abort_propagates_from_submit(tmp_path):
    """A task that exhausts its budget must abort submit() instead of
    wedging the rendezvous wait."""
    import importlib

    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    with pytest.raises(JobAborted):
        submit_mod.main(
            ["--cluster", "local", "--num-workers", "1",
             "--local-num-attempt", "1",
             "--host-ip", "127.0.0.1", "false"]
        )
