"""The unified telemetry subsystem (ISSUE 4): registry semantics
(thread-sharded merge, histogram bucket edges, label cardinality cap),
exporters (Prometheus exposition, JSON, Reporter), tracker-wide
aggregation over real heartbeats, and the migrated io_stats() view
staying bit-compatible with the pre-registry goldens."""

import json
import re
import threading
import time

import pytest

from dmlc_core_tpu.telemetry import (
    ClusterAggregator,
    MetricRegistry,
    Reporter,
    default_registry,
    log_bounds,
    merge_snapshots,
    render_key,
    split_key,
    to_json,
    to_prometheus,
)


# -- registry semantics -------------------------------------------------------

def test_counter_merge_under_concurrent_writers():
    reg = MetricRegistry()
    c = reg.counter("t.hits")
    barrier = threading.Barrier(8)

    def writer():
        barrier.wait()
        for _ in range(10_000):
            c.inc()

    threads = [threading.Thread(target=writer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 80_000
    # contributions of finished threads survive: counters are cumulative
    assert reg.snapshot()["counters"]["t.hits"] == 80_000
    # ...but their cells are folded into the retired total, so memory
    # does not grow with thread churn
    assert len(c._cells) <= 1  # only this (reading) thread, if any
    assert c.value() == 80_000  # folding is idempotent


def test_counter_float_and_monotonic():
    reg = MetricRegistry()
    c = reg.counter("t.secs")
    c.inc(0.25)
    c.inc(0.5)
    assert c.value() == 0.75
    with pytest.raises(ValueError):
        c.inc(-1)


def test_get_or_create_returns_same_series():
    reg = MetricRegistry()
    a = reg.counter("t.x", labels={"k": "1"})
    b = reg.counter("t.x", labels={"k": "1"})
    other = reg.counter("t.x", labels={"k": "2"})
    assert a is b and a is not other
    with pytest.raises(ValueError):
        reg.gauge("t.x")  # same name, different type
    with pytest.raises(ValueError):
        reg.counter("bad name!")


def test_gauge_set_and_callable():
    reg = MetricRegistry()
    g = reg.gauge("t.depth")
    g.set(3)
    g.inc()
    assert g.value() == 4
    g.set_fn(lambda: 7)
    assert g.value() == 7
    assert reg.snapshot()["gauges"]["t.depth"] == 7


def test_histogram_bucket_edges_le_semantics():
    reg = MetricRegistry()
    h = reg.histogram("t.lat", bounds=[1.0, 2.0, 4.0])
    for v in (0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 5.0, 100.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["le"] == [1.0, 2.0, 4.0]
    # v <= bound lands in the bucket (Prometheus le); 5.0/100.0 overflow
    assert snap["n"] == [2, 2, 2, 2]
    assert snap["count"] == 8
    assert snap["sum"] == pytest.approx(117.0)
    assert snap["min"] == 0.5 and snap["max"] == 100.0
    assert set(snap) >= {"p50", "p90", "p99"}


def test_histogram_default_log_buckets_and_percentiles():
    reg = MetricRegistry()
    h = reg.histogram("t.dur")
    for _ in range(100):
        h.observe(1e-3)
    snap = h.snapshot()
    assert snap["count"] == 100
    # all mass in one log2 bucket → p50 interpolates inside it
    assert 5e-4 <= snap["p50"] <= 2e-3
    bounds = log_bounds(1e-6, 100.0)
    assert snap["le"] == list(bounds)
    assert all(b == pytest.approx(a * 2) for a, b in zip(bounds, bounds[1:]))


def test_histogram_concurrent_observers_exact_count():
    reg = MetricRegistry()
    h = reg.histogram("t.conc", bounds=[0.5, 1.5])

    def obs():
        for _ in range(5000):
            h.observe(1.0)

    threads = [threading.Thread(target=obs) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = h.snapshot()
    assert snap["count"] == 20_000 and snap["n"] == [0, 20_000, 0]
    # dead observers' shards folded; totals unchanged on re-read
    assert len(h._cells) == 0
    assert h.snapshot()["count"] == 20_000


def test_label_cardinality_cap(monkeypatch):
    monkeypatch.setenv("DMLC_METRIC_LABEL_CAP", "4")
    reg = MetricRegistry()
    for i in range(10):
        reg.counter("t.byuser", labels={"user": str(i)}).inc()
    snap = reg.snapshot()["counters"]
    series = [k for k in snap if k.startswith("t.byuser")]
    # 4 real series + the one overflow series everything else collapsed to
    assert len(series) == 5
    assert snap['t.byuser{overflow="true"}'] == 6
    assert snap["telemetry.label_overflow"] == 6


def test_scoped_view_delta():
    reg = MetricRegistry()
    a = reg.counter("io.a")
    b = reg.counter("net.b")
    a.inc(5)
    view = reg.scoped("io.")
    a.inc(2)
    b.inc(9)
    d = view.delta()
    assert d == {"io.a": 2}
    # a series born after the base snapshot counts from zero
    reg.counter("io.new").inc(3)
    assert view.delta()["io.new"] == 3
    # rebase(): deltas restart from zero, counters stay monotonic
    view.rebase()
    assert view.delta() == {"io.a": 0.0, "io.new": 0.0}
    a.inc()
    assert view.delta()["io.a"] == 1
    # exact-series views read only what they name
    named = reg.scoped(names=["net.b"])
    b.inc(4)
    assert named.delta() == {"net.b": 4}


def test_render_split_key_roundtrip():
    key = render_key("a.b", {"z": 'he said "hi"', "a": "x\\y"})
    name, labels = split_key(key)
    assert name == "a.b"
    assert labels == {"z": 'he said "hi"', "a": "x\\y"}
    assert split_key("plain") == ("plain", {})


# -- exporters ----------------------------------------------------------------

def _sample_registry():
    reg = MetricRegistry()
    reg.counter("io.retry.retries", help="retries healed").inc(3)
    reg.gauge("staging.ring_depth").set(3)
    h = reg.histogram(
        "staging.stage_seconds", labels={"stage": "host_pull"},
        bounds=[0.001, 0.01, 0.1],
    )
    for v in (0.0005, 0.005, 0.05, 0.5):
        h.observe(v)
    return reg


_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? [^ ]+$"
)


def test_prometheus_exposition_parses():
    text = to_prometheus(_sample_registry())
    saw_types = {}
    samples = {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            saw_types[name] = kind
            continue
        if line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), line
        series, value = line.rsplit(" ", 1)
        samples[series] = float(value)
    assert saw_types["dmlc_io_retry_retries"] == "counter"
    assert saw_types["dmlc_staging_ring_depth"] == "gauge"
    assert saw_types["dmlc_staging_stage_seconds"] == "histogram"
    assert samples["dmlc_io_retry_retries"] == 3
    # histogram buckets are CUMULATIVE and end with le="+Inf" == _count
    buckets = [
        (s, v) for s, v in samples.items()
        if s.startswith("dmlc_staging_stage_seconds_bucket")
    ]
    counts = [v for _s, v in buckets]
    assert counts == sorted(counts) and counts == [1, 2, 3, 4]
    inf = [s for s, _ in buckets if 'le="+Inf"' in s]
    assert len(inf) == 1
    assert samples['dmlc_staging_stage_seconds_count{stage="host_pull"}'] == 4


def test_prometheus_renders_non_finite_values():
    """A broken gauge probe yields NaN by contract; the render must
    spell it NaN (exposition spec), not crash the scrape."""
    reg = MetricRegistry()
    g = reg.gauge("t.broken")
    g.set_fn(lambda: 1 / 0)  # probe raises -> value() is NaN
    reg.gauge("t.inf").set(float("inf"))
    text = to_prometheus(reg)
    assert "dmlc_t_broken NaN" in text
    assert "dmlc_t_inf +Inf" in text
    # ...and the heartbeat sanitizer drops them (json.dumps(nan) is not
    # valid JSON for strict report consumers)
    agg = ClusterAggregator()
    agg.update(0, {"gauges": {"g": float("nan"), "ok": 2.0}})
    assert agg.report()["cluster"]["gauges"] == {"ok": 2.0}


def test_json_snapshot_and_merge():
    snap = to_json(_sample_registry())
    json.dumps(snap)  # JSON-able as-is
    merged = merge_snapshots([snap, snap])
    assert merged["counters"]["io.retry.retries"] == 6
    key = 'staging.stage_seconds{stage="host_pull"}'
    assert merged["histograms"][key]["count"] == 8
    assert merged["histograms"][key]["n"] == [2, 2, 2, 2]
    assert merged["histograms"][key]["max"] == 0.5
    assert "p50" in merged["histograms"][key]
    # a rank with mismatched edges is skipped, not corrupting the merge
    bad = json.loads(json.dumps(snap))
    bad["histograms"][key]["le"] = [1, 2, 3]
    merged2 = merge_snapshots([snap, bad])
    assert merged2["histograms"][key]["count"] == 4


def test_reporter_interval_flush_and_close_dump(tmp_path):
    reg = MetricRegistry()
    reg.counter("t.n").inc(1)
    out = tmp_path / "telemetry.jsonl"
    rep = Reporter(reg, interval=0.05, path=str(out))
    deadline = time.perf_counter() + 5.0
    while rep.flushes == 0 and time.perf_counter() < deadline:
        time.sleep(0.01)
    assert rep.flushes >= 1, "interval flush never fired"
    reg.counter("t.n").inc(41)
    rep.close()
    rep.close()  # idempotent
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(lines) == rep.flushes >= 2
    # the close-time dump sees the final counter value
    assert lines[-1]["snapshot"]["counters"]["t.n"] == 42
    assert lines[-1]["uptime_secs"] >= 0


def test_default_registry_is_process_global():
    assert default_registry() is default_registry()
    c = default_registry().counter("test.telemetry.global")
    before = c.value()
    c.inc()
    assert default_registry().counter("test.telemetry.global").value() == (
        before + 1
    )


# -- tracker aggregation over real heartbeats ---------------------------------

def _http_get(port, path):
    import socket

    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(
            f"GET {path} HTTP/1.1\r\nHost: 127.0.0.1\r\n"
            "Connection: close\r\n\r\n".encode()
        )
        buf = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, body = buf.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, body.decode()


def test_cluster_aggregator_merges_per_rank():
    agg = ClusterAggregator()
    agg.update(0, json.dumps({"counters": {"app.rows": 10}}))
    agg.update(1, {"counters": {"app.rows": 32}, "gauges": {"q": 1}})
    agg.update(1, {"counters": {"app.rows": 40}})  # latest-per-rank wins
    agg.update(0, "not json")  # dropped, not fatal
    report = agg.report()
    assert report["n_ranks"] == 2
    assert report["cluster"]["counters"]["app.rows"] == 50
    assert report["per_rank"]["0"]["counters"]["app.rows"] == 10
    text = agg.prometheus()
    assert "dmlc_app_rows 50" in text
    assert 'dmlc_app_rows{rank="0"} 10' in text
    assert 'dmlc_app_rows{rank="1"} 40' in text
    # ONE valid exposition: exactly one # TYPE line per metric family
    # (a scraper rejects duplicate TYPE lines / interleaved families)
    type_names = [
        ln.split()[2] for ln in text.splitlines() if ln.startswith("# TYPE")
    ]
    assert len(type_names) == len(set(type_names)), type_names


def test_cluster_aggregator_sanitizes_malformed_series():
    """A buggy/hostile worker's type-skewed payload costs its bad
    series only — later merges, scrapes and the end-of-job report keep
    working (the 'aggregator validates/drops' contract)."""
    agg = ClusterAggregator()
    agg.update(0, {"counters": {"good": 1, "bad": "abc", "b2": None}})
    agg.update(1, {"histograms": {"h": {}, "ok": {
        "le": [1.0], "n": [1, 0], "count": 1, "sum": 0.5}}})
    agg.update(2, {"counters": "nope", "gauges": {"g": True}})
    # empty-bounds histograms pass the arithmetic shape check but would
    # crash percentile math — dropped by the sanitizer
    agg.update(3, {"histograms": {"empty": {
        "le": [], "n": [5], "count": 5, "sum": 1.0, "max": 2.0}}})
    report = agg.report()  # must not raise
    assert report["cluster"]["counters"] == {"good": 1}
    assert list(report["cluster"]["histograms"]) == ["ok"]
    assert report["cluster"]["gauges"] == {}  # bools are not numbers
    agg.prometheus()  # renders without raising


def test_percentiles_degrade_on_foreign_empty_bounds():
    """percentiles() over a foreign snapshot with le=[] degrades to the
    known max instead of crashing the scrape (registries themselves
    reject empty bounds at registration)."""
    from dmlc_core_tpu.telemetry.registry import percentiles

    out = percentiles({"le": [], "n": [5], "count": 5, "sum": 1.0, "max": 2.0})
    assert out == {"p50": 2.0, "p90": 2.0, "p99": 2.0}
    with pytest.raises(ValueError):
        MetricRegistry().histogram("t.empty", bounds=[])


def test_prometheus_families_stay_contiguous():
    """'name' vs 'name_out': '_' sorts before '{', so a raw-key sort
    would split the shorter family around the longer one — every
    family's samples must form one contiguous group."""
    agg = ClusterAggregator()
    agg.update(0, {"counters": {"staging.rows": 1, "staging.rows_out": 2}})
    agg.update(1, {"counters": {"staging.rows": 3, "staging.rows_out": 4}})
    text = agg.prometheus()
    fams = [
        ln.split("{")[0].split(" ")[0]
        for ln in text.splitlines()
        if ln.strip() and not ln.startswith("#")
    ]
    seen = []
    for f in fams:
        if seen and seen[-1] == f:
            continue
        assert f not in seen, (f, fams)  # family re-opened = split
        seen.append(f)


def test_tracker_rejects_out_of_range_metrics_rank():
    """A fabricated rank must not mint unbounded per-rank snapshots:
    cmd=metrics is bounded like shutdown (0 <= rank < n_workers)."""
    import socket as socket_mod

    from dmlc_core_tpu.tracker.client import RabitWorker
    from dmlc_core_tpu.tracker.protocol import MAGIC, FramedSocket
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    w = RabitWorker("127.0.0.1", tracker.port, jobid="0")
    w.start(world_size=1)

    def send_metrics(rank, payload):
        fs = FramedSocket(
            socket_mod.create_connection(("127.0.0.1", tracker.port), 10)
        )
        fs.send_int(MAGIC)
        assert fs.recv_int() == MAGIC
        fs.send_int(rank)
        fs.send_int(-1)
        fs.send_str("x")
        fs.send_str("metrics")
        fs.send_str(json.dumps(payload))
        fs.close()

    send_metrics(2**31 - 1, {"counters": {"bogus": 1}})
    send_metrics(-7, {"counters": {"bogus": 1}})
    w.heartbeat({"counters": {"real": 1}})
    deadline = time.perf_counter() + 10
    while tracker.metrics.updates < 1 and time.perf_counter() < deadline:
        time.sleep(0.02)
    time.sleep(0.2)  # give the bogus frames time to be (dropped)
    assert set(tracker.metrics.per_rank()) == {0}
    w.shutdown()
    tracker.join()
    tracker.close()


def test_heartbeat_before_start_raises():
    """heartbeat() without a rank would be silently discarded by the
    tracker; the client fails loudly instead."""
    from dmlc_core_tpu.tracker.client import RabitWorker

    w = RabitWorker("127.0.0.1", 1, jobid="x")
    with pytest.raises(RuntimeError, match="before start"):
        w.heartbeat({"counters": {}})


def test_tracker_metrics_endpoint_multi_worker():
    """Two real RabitWorkers heartbeat snapshots; the tracker's local
    /metrics endpoint serves per-rank series + cluster totals, and the
    end-of-job report aggregates them."""
    from dmlc_core_tpu.tracker.client import RabitWorker
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    n = 2
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)
    assert tracker.metrics_port is not None

    ranks = {}
    errors = []

    def one(i):
        try:
            w = RabitWorker("127.0.0.1", tracker.port, jobid=str(i))
            rank = w.start(world_size=n if i == 0 else -1)
            ranks[i] = rank
            w.heartbeat(
                {
                    "counters": {"worker.rows": 100 * (rank + 1)},
                    "histograms": {
                        "worker.lat": {
                            "le": [1.0, 2.0],
                            "n": [rank + 1, 0, 0],
                            "count": rank + 1,
                            "sum": float(rank + 1),
                        }
                    },
                }
            )
            # wait until the tracker's state thread applied both updates
            # (heartbeats ride the same event queue as everything else)
            deadline = time.perf_counter() + 10
            while (
                tracker.metrics.updates < n
                and time.perf_counter() < deadline
            ):
                time.sleep(0.02)
            w.shutdown()
        except Exception as e:  # noqa: BLE001
            errors.append((i, e))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    # scrape while the job is live (that is the point of the endpoint)
    deadline = time.perf_counter() + 10
    while tracker.metrics.updates < n and time.perf_counter() < deadline:
        time.sleep(0.02)
    assert tracker.metrics.updates >= n

    status, body = _http_get(tracker.metrics_port, "/metrics")
    assert status == 200
    assert "dmlc_worker_rows 300" in body  # cluster total: 100 + 200
    assert 'dmlc_worker_rows{rank="0"} 100' in body
    assert 'dmlc_worker_rows{rank="1"} 200' in body
    # merged histogram: bucket counts added across ranks
    assert 'dmlc_worker_lat_bucket{le="1",rank="0"} 1' in body
    assert 'dmlc_worker_lat_count 3' in body
    # scrape body is one valid exposition (no duplicate TYPE lines)
    type_names = [
        ln.split()[2] for ln in body.splitlines() if ln.startswith("# TYPE")
    ]
    assert len(type_names) == len(set(type_names)), type_names

    status, body = _http_get(tracker.metrics_port, "/metrics.json")
    assert status == 200
    report = json.loads(body)
    assert report["n_ranks"] == n
    assert report["cluster"]["counters"]["worker.rows"] == 300
    assert report["cluster"]["histograms"]["worker.lat"]["count"] == 3

    status, _ = _http_get(tracker.metrics_port, "/nope")
    assert status == 404

    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    tracker.join()
    # end-of-job report is kept on the tracker after completion
    assert tracker.metrics_report is not None
    assert tracker.metrics_report["cluster"]["counters"]["worker.rows"] == 300
    tracker.close()


def test_tracker_end_of_job_report_file(tmp_path, monkeypatch):
    from dmlc_core_tpu.tracker.client import RabitWorker
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    path = tmp_path / "job_metrics.json"
    monkeypatch.setenv("DMLC_METRICS_REPORT", str(path))
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    w = RabitWorker("127.0.0.1", tracker.port, jobid="0")
    w.start(world_size=1)
    w.heartbeat({"counters": {"job.done": 1}})
    deadline = time.perf_counter() + 10
    while tracker.metrics.updates < 1 and time.perf_counter() < deadline:
        time.sleep(0.02)
    w.shutdown()
    tracker.join()
    tracker.close()
    report = json.loads(path.read_text())
    assert report["cluster"]["counters"]["job.done"] == 1
    assert report["n_ranks"] == 1


def test_heartbeat_defaults_to_process_registry():
    """heartbeat() with no args ships the default registry snapshot."""
    from dmlc_core_tpu.tracker.client import RabitWorker
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    marker = default_registry().counter("test.heartbeat.marker")
    marker.inc(7)
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    w = RabitWorker("127.0.0.1", tracker.port, jobid="0")
    w.start(world_size=1)
    w.heartbeat()
    deadline = time.perf_counter() + 10
    while tracker.metrics.updates < 1 and time.perf_counter() < deadline:
        time.sleep(0.02)
    snap = tracker.metrics.per_rank()[0]
    assert snap["counters"]["test.heartbeat.marker"] >= 7
    w.shutdown()
    tracker.join()
    tracker.close()


# -- migrated io_stats(): bit-compatible view over the registry ---------------

def test_retry_stats_view_matches_registry_counters():
    from dmlc_core_tpu.io import retry

    retry.reset_stats()
    assert retry.stats() == {
        "retries": 0,
        "backoff_secs": 0.0,
        "faults_injected": 0,
    }
    before_reg = default_registry().snapshot()["counters"]
    policy = retry.RetryPolicy(
        max_attempts=5, base_secs=0.01, cap_secs=0.01, sleep=lambda s: None
    )
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise ConnectionResetError("boom")
        return "ok"

    assert policy.run(flaky) == "ok"
    retry.count_fault_injected(2)
    s = retry.stats()
    # the io_stats() golden shape: int counts, rounded float backoff
    assert s["retries"] == 3 and isinstance(s["retries"], int)
    assert s["faults_injected"] == 2 and isinstance(s["faults_injected"], int)
    assert isinstance(s["backoff_secs"], float) and s["backoff_secs"] > 0
    # the registry carries the same increments (monotonic, never reset)
    after_reg = default_registry().snapshot()["counters"]
    assert after_reg["io.retry.retries"] - before_reg.get(
        "io.retry.retries", 0
    ) == 3
    assert after_reg["io.faults.injected"] - before_reg.get(
        "io.faults.injected", 0
    ) == 2
    # delta view composes exactly as before the migration
    snap = retry.stats()
    retry.count_fault_injected(1)
    assert retry.stats_delta(snap) == {
        "retries": 0,
        "backoff_secs": 0.0,
        "faults_injected": 1,
    }
    retry.reset_stats()
    assert retry.stats()["faults_injected"] == 0


def test_split_io_stats_golden_keys(tmp_path):
    """InputSplitBase.io_stats() keeps the pre-migration shape: mode +
    the three retry-delta keys (plus the ISSUE 9 ``reopens`` stream
    re-establishment delta), ints/floats, zero on a clean local read."""
    from dmlc_core_tpu.io import split as io_split

    p = tmp_path / "x.txt"
    p.write_text("a\nb\nc\n")
    s = io_split.create(str(p), type="text", threaded=False)
    while s.next_record() is not None:
        pass
    stats = s.io_stats()
    s.close()
    assert stats == {
        "mode": "sequential",
        "reopens": 0,
        "retries": 0,
        "backoff_secs": 0.0,
        "faults_injected": 0,
    }


def test_wrapper_splits_io_stats_always_dict(tmp_path):
    """ISSUE 4 satellite: threaded/cached/shuffle wrappers return a
    (possibly empty) dict even over a base without io_stats."""
    from dmlc_core_tpu.io import split as io_split

    class Bare(io_split.InputSplit):
        """Minimal base with no io_stats attribute."""

        def __init__(self):
            self.chunks = [b"a\n", b"b\n"]
            self.i = 0

        def next_chunk(self):
            if self.i >= len(self.chunks):
                return None
            c = self.chunks[self.i]
            self.i += 1
            return c

        def next_record(self):
            return self.next_chunk()

        def before_first(self):
            self.i = 0

        def reset_partition(self, part_index, num_parts):
            self.i = 0

        def extract_records(self, chunk):
            return iter([chunk])

        def close(self):
            pass

    t = io_split.ThreadedInputSplit(Bare())
    assert t.io_stats() == {}
    t.close()
    c = io_split.CachedInputSplit(Bare(), str(tmp_path / "cache.bin"))
    assert c.io_stats() == {}
    c.close()
    sh = io_split.InputSplitShuffle(Bare(), 0, 1, 2)
    assert sh.io_stats() == {}
    sh.close()
    # the real splits keep their full stats through the wrappers
    p = tmp_path / "y.txt"
    p.write_text("a\nb\n")
    t2 = io_split.create(str(p), type="text", threaded=True)
    stats = t2.io_stats()
    assert isinstance(stats, dict) and stats["mode"] == "sequential"
    t2.close()


def test_split_registry_mirrors_tick(tmp_path):
    """The indexed split's per-instance I/O-shape counters also feed the
    process-global io.split.* registry series."""
    import numpy as np

    from dmlc_core_tpu.io import split as io_split
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream

    rec = tmp_path / "d.rec"
    idx = tmp_path / "d.rec.idx"
    with FileStream(str(rec), "w") as f, FileStream(str(idx), "w") as fi:
        w = IndexedRecordIOWriter(f, fi)
        for i in range(32):
            w.write_record(np.int32(i).tobytes() * 3, key=i)
    before = default_registry().snapshot()["counters"]
    s = io_split.create(
        f"{rec}?index={idx}", type="recordio", shuffle="record",
        threaded=False, seed=3,
    )
    while s.next_batch(8) is not None:
        pass
    stats = s.io_stats()
    s.close()
    after = default_registry().snapshot()["counters"]
    assert stats["records"] == 32
    assert after["io.split.records"] - before.get("io.split.records", 0) == 32
    assert (
        after["io.split.spans"] - before.get("io.split.spans", 0)
        == stats["spans"]
    )
    assert (
        after["io.split.bytes_read"] - before.get("io.split.bytes_read", 0)
        == stats["bytes_read"]
    )


def test_staging_stage_histograms_fed(tmp_path):
    """A staged epoch leaves duration samples in the
    staging.stage_seconds{stage=...} histograms and ticks the staging
    counters — the PR 3 sums are now distributions too."""
    jax = pytest.importorskip("jax")
    import numpy as np

    from dmlc_core_tpu.staging import (
        BatchSpec,
        StagingPipeline,
        dense_batches,
        drain_close,
    )

    p = tmp_path / "d.libsvm"
    lines = []
    rng = np.random.default_rng(0)
    for i in range(64):
        feats = " ".join(f"{j}:{rng.normal():.4f}" for j in range(4))
        lines.append(f"{i % 2} {feats}")
    p.write_text("\n".join(lines) + "\n")
    before = default_registry().snapshot()
    spec = BatchSpec(batch_size=16, layout="dense", num_features=5)
    stream = dense_batches(str(p), spec)
    pipe = StagingPipeline(stream, device=jax.devices("cpu")[0])
    n = sum(1 for _ in pipe)
    drain_close(pipe, stream)
    assert n == 4
    after = default_registry().snapshot()
    key = 'staging.stage_seconds{stage="host_pull"}'
    grew = (
        after["histograms"][key]["count"]
        - before["histograms"].get(key, {}).get("count", 0)
    )
    assert grew >= n
    assert (
        after["counters"]["staging.rows"]
        - before["counters"].get("staging.rows", 0)
    ) == 64
    # io_stats() keeps its merged shape (source stats + staging block)
    assert "staging" in pipe.io_stats()


# -- ISSUE 14 satellites -------------------------------------------------------


def test_serve_metrics_http_concurrent_scrapes_and_idempotent_close():
    """serve_metrics_http under 8 concurrent scrapers answers every
    request with a parseable body, and BOTH halves of teardown are
    idempotent — shutdown() + a double server_close() must be safe
    (teardown paths race: atexit vs explicit close vs SIGTERM)."""
    import urllib.request

    from dmlc_core_tpu.telemetry import serve_metrics_http

    reg = MetricRegistry()
    reg.counter("io.split.records").inc(42)
    server = serve_metrics_http(
        0, registry=reg, json_provider=lambda: {"ok": True}
    )
    port = server.server_address[1]
    results, errors = [], []

    def scrape(path):
        try:
            for _ in range(5):
                with urllib.request.urlopen(  # noqa: L006 (loopback test scrape, not remote IO)
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ) as resp:
                    results.append(resp.read())
        except Exception as e:  # collected, asserted below
            errors.append(e)

    threads = [
        threading.Thread(target=scrape, args=(p,))
        for p in ("/metrics", "/metrics.json", "/metrics", "/stats")
        for _ in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(results) == 40
    assert any(b"dmlc_io_split_records 42" in r for r in results)
    server.shutdown()
    server.server_close()
    server.server_close()  # second close: no-op, no EBADF/double-free
    server.shutdown()      # and shutdown after close stays safe


def test_cluster_aggregator_skips_mismatched_histogram_edges():
    """The satellite's aggregator coverage: two ranks whose histogram
    EDGES disagree (version skew) — the merge keeps the first and
    skips the rest; every other series still merges."""
    agg = ClusterAggregator()
    agg.update(0, {
        "counters": {"c": 1}, "gauges": {},
        "histograms": {"h": {"le": [1.0, 2.0], "n": [1, 2, 0],
                             "count": 3, "sum": 3.0}},
    })
    agg.update(1, {
        "counters": {"c": 2}, "gauges": {},
        "histograms": {"h": {"le": [1.0, 4.0], "n": [5, 5, 0],
                             "count": 10, "sum": 9.0}},
    })
    cluster = agg.cluster()
    assert cluster["counters"]["c"] == 3  # counters still merged
    # the mismatched histogram kept the FIRST rank's buckets only
    assert cluster["histograms"]["h"]["count"] == 3
    assert cluster["histograms"]["h"]["le"] == [1.0, 2.0]
    # and the scrape keeps working end to end
    assert "dmlc_h_bucket" in agg.prometheus()


def test_cluster_aggregator_accepts_restart_timeseries():
    """Heartbeat time-series samples from a rank that restarts mid-job:
    the stale replayed tail is dropped (sample clock never goes
    backwards), the fresh post-relaunch samples extend the SAME rank's
    series, and windowed rates stay non-negative across the counter
    reset."""
    agg = ClusterAggregator()
    snap = {"counters": {}, "gauges": {}, "histograms": {}}
    agg.update(3, {**snap, "timeseries": [
        {"t": 50.0, "seq": 1, "counters": {"io.split.records": 900.0},
         "gauges": {}, "histograms": {}},
        {"t": 52.0, "seq": 2, "counters": {"io.split.records": 1800.0},
         "gauges": {}, "histograms": {}},
    ]})
    # relaunch: seq and counters restart; first sample replays t=51
    agg.update(3, {**snap, "timeseries": [
        {"t": 51.0, "seq": 1, "counters": {"io.split.records": 100.0},
         "gauges": {}, "histograms": {}},
        {"t": 55.0, "seq": 2, "counters": {"io.split.records": 400.0},
         "gauges": {}, "histograms": {}},
    ]})
    assert agg.timeseries.dropped_stale == 1
    view = agg.windowed(60.0)["per_rank"]["3"]
    assert view["samples"] == 3
    assert view["counters"]["io.split.records"]["delta"] >= 0
    ts_times = [
        s["t"]
        for s in agg.report()["timeseries"]["per_rank"]["3"]
    ]
    assert ts_times == sorted(ts_times)  # monotone after the relaunch


def test_gauge_set_max_and_registry_peak_reset():
    """The peak-gauge story (satellite): set_max keeps the high-water
    mark, reset_peak_gauges rewinds ONLY set_max-style gauges at a
    measurement-scope boundary — live inc/dec gauges are untouched."""
    reg = MetricRegistry()
    peak = reg.gauge("io.fetch.concurrency_peak")
    live = reg.gauge("dsserve.queue_depth")
    live.inc(4)
    peak.set_max(8)
    peak.set_max(3)       # lower reading never clobbers the peak
    assert peak.value() == 8
    assert reg.peak_gauge_values() == {"io.fetch.concurrency_peak": 8.0}
    assert reg.reset_peak_gauges() == 1
    assert peak.value() == 0.0
    assert live.value() == 4.0  # live accounting survived the rewind
    peak.set_max(5)       # the next scope records ITS peak
    assert peak.value() == 5.0
