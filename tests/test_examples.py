"""The examples/ scripts must keep running — they are the README's
quick-start and double as end-to-end smoke coverage of the public API
(the reference keeps example/*.cc building in CI via its Makefile).

Each runs as a subprocess pinned to the CPU backend via a pre-import
``jax.config.update`` shim: on axon TPU build hosts the force-registered
TPU plugin overrides ``JAX_PLATFORMS=cpu``, so an env var alone would
silently put these smoke tests on the real (throttled, shared) chip —
the same pinning every other subprocess test in this repo uses."""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")

# pin BEFORE the example's own jax import wins the backend choice
_RUNNER = """
import runpy, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.argv = sys.argv[1:]
runpy.run_path(sys.argv[0], run_name="__main__")
"""


def run_example(script, args=(), timeout=240, cwd=None, extra_env=None):
    env = os.environ.copy()
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-c", _RUNNER,
         os.path.join(EXAMPLES, script), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=cwd,
    )


def test_parameter_demo():
    proc = run_example(
        "parameter_demo.py", ["learning_rate=0.1", "name=smoke"]
    )
    assert proc.returncode == 0, proc.stderr
    assert "initialized:" in proc.stdout
    assert "Step size." in proc.stdout  # only the generated docs print this


@pytest.mark.slow
def test_train_higgs(tmp_path):
    shutil.rmtree("/tmp/higgs_ckpts", ignore_errors=True)
    try:
        proc = run_example(
            "train_higgs.py", [str(tmp_path / "higgs.libsvm")],
            cwd=str(tmp_path),
        )
        assert proc.returncode == 0, proc.stderr
        assert "epoch" in proc.stdout and "loss=" in proc.stdout
    finally:
        shutil.rmtree("/tmp/higgs_ckpts", ignore_errors=True)


@pytest.mark.slow
def test_train_criteo_rec_dynamic_shards(tmp_path):
    """DMLC_DYNAMIC_SHARDS=1: the trainer pulls tracker-leased
    micro-shards instead of its static rank shard (docs/sharding.md) —
    end-to-end through the rendezvous, the lease protocol and the
    fused staging path, with the ledger confirming every micro-shard
    was completed exactly once."""
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    shutil.rmtree("/tmp/criteo_ckpts_v2", ignore_errors=True)
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    try:
        proc = run_example(
            "train_criteo_rec.py", [str(tmp_path / "c.rec")],
            cwd=str(tmp_path),
            extra_env={
                "DMLC_TRACKER_URI": "127.0.0.1",
                "DMLC_TRACKER_PORT": str(tracker.port),
                "DMLC_NUM_WORKER": "1",
                "DMLC_TASK_ID": "0",
                "DMLC_DYNAMIC_SHARDS": "1",
            },
        )
        assert proc.returncode == 0, proc.stderr
        assert "epoch" in proc.stdout
        summary = tracker.shards.summary()
        # 3 epochs × oversplit micro-shards, each exactly-once
        assert summary["completed"] == summary["granted"] > 0
        assert summary["duplicates"] == 0
    finally:
        tracker.close()
        shutil.rmtree("/tmp/criteo_ckpts_v2", ignore_errors=True)


@pytest.mark.slow
def test_train_criteo_rec_multihost_sgd(tmp_path):
    """Two workers under a real tracker = TRUE multi-host SGD
    (docs/collectives.md): per-step gradients allreduced by the
    collective engine, one shared update — both ranks must finish with
    BIT-IDENTICAL params (DMLC_SGD_OUT publishes each rank's final
    model; DMLC_SGD_PATH=tree pins the deterministic fold order)."""
    import numpy as np

    from dmlc_core_tpu.tracker.tracker import RabitTracker

    # generate the shard once up front: two racing workers would both
    # see the missing file and interleave their synth writes
    shutil.rmtree("/tmp/criteo_ckpts_v2", ignore_errors=True)
    proc = run_example(
        "train_criteo_rec.py", [str(tmp_path / "c.rec")],
        cwd=str(tmp_path), extra_env={"DMLC_SGD_EPOCHS": "0"},
    )
    assert proc.returncode == 0, proc.stderr
    shutil.rmtree("/tmp/criteo_ckpts_v2", ignore_errors=True)
    out = str(tmp_path / "model")
    tracker = RabitTracker("127.0.0.1", 2)
    tracker.start(2)
    try:
        env = os.environ.copy()
        env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        env.update({
            "DMLC_TRACKER_URI": "127.0.0.1",
            "DMLC_TRACKER_PORT": str(tracker.port),
            "DMLC_SGD_EPOCHS": "1",
            "DMLC_SGD_PATH": "tree",
            "DMLC_SGD_OUT": out,
        })
        procs = []
        for task in range(2):
            e = dict(env, DMLC_TASK_ID=str(task))
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _RUNNER,
                 os.path.join(EXAMPLES, "train_criteo_rec.py"),
                 str(tmp_path / "c.rec")],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=e, cwd=str(tmp_path),
            ))
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for task, p in enumerate(procs):
            assert p.returncode == 0, (
                f"worker {task} failed:\n{outs[task][-2000:]}"
            )
    finally:
        tracker.close()
    models = [np.load(f"{out}.rank{r}.npz") for r in range(2)]
    keys = sorted(models[0].files)
    assert sorted(models[1].files) == keys
    for k in keys:
        assert np.array_equal(models[0][k], models[1][k]), (
            f"param {k!r} diverged across ranks — the shared update is "
            "not shared"
        )
    # a real multi-worker run actually stepped (gradients flowed)
    assert int(models[0]["gstep"]) > 0


@pytest.mark.slow
def test_train_criteo_rec(tmp_path):
    shutil.rmtree("/tmp/criteo_ckpts", ignore_errors=True)
    try:
        proc = run_example(
            "train_criteo_rec.py", [str(tmp_path / "c.rec")],
            cwd=str(tmp_path),
        )
        assert proc.returncode == 0, proc.stderr
        assert "epoch" in proc.stdout
        # the synthetic shard publishes its count index for shuffled epochs
        assert os.path.exists(str(tmp_path / "c.rec") + ".idx")
    finally:
        shutil.rmtree("/tmp/criteo_ckpts", ignore_errors=True)
