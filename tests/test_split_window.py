"""Windowed shuffle with coalesced I/O (ISSUE 1 tentpole).

Covers the mode's whole contract: coverage (per-epoch multiset equals
the sequential read), determinism (epoch order is a function of
(seed, epoch) — in fact identical to shuffle='record' — and
before_first rebuilds it exactly), window-boundary resume with a loud
failure inside a window, the span planner's merge/gap semantics (unit
tested directly), multi-file spans, sharding exactness, URI sugar, and
the io_stats counters that prove coalescing (spans ≪ records) and the
local pread fast path (seeks == 0).
"""

import os

import pytest

from dmlc_core_tpu.io import (
    IndexedRecordIOSplitter,
    MemoryStream,
    RecordIOWriter,
    TemporaryDirectory,
)
from dmlc_core_tpu.io.split import plan_coalesced_spans
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.utils import Error


def make_indexed_rec(tmp, records, name="data"):
    ms = MemoryStream()
    w = RecordIOWriter(ms)
    offsets = []
    for r in records:
        offsets.append(ms.tell())
        w.write_record(r)
    p = os.path.join(tmp, f"{name}.rec")
    with open(p, "wb") as f:
        f.write(ms.getvalue())
    idx = os.path.join(tmp, f"{name}.idx")
    with open(idx, "w") as f:
        for i, off in enumerate(offsets):
            f.write(f"{i} {off}\n")
    return p, idx


def drain(split):
    out = []
    while True:
        rec = split.next_record()
        if rec is None:
            return out
        out.append(bytes(rec))


def records_of(n, tag="w"):
    return [f"{tag}rec{i:03d}".encode() * (i % 5 + 1) for i in range(n)]


# -- span planner (unit) -----------------------------------------------------
def test_planner_merges_adjacent_and_respects_gap():
    # records at [0,10) [10,20) [25,35) [200,210): gap 5 between the
    # 2nd and 3rd, gap 165 before the 4th
    entries = [(25, 10, 2), (0, 10, 0), (200, 10, 3), (10, 10, 1)]
    # gap threshold 0: only byte-adjacent records merge
    spans = plan_coalesced_spans(entries, 0)
    assert [(b, e) for b, e, _m in spans] == [(0, 20), (25, 35), (200, 210)]
    assert spans[0][2] == [(0, 10, 0), (10, 10, 1)]  # offset-sorted members
    # gap threshold 5: the 5-byte hole merges, the 165-byte one doesn't
    spans = plan_coalesced_spans(entries, 5)
    assert [(b, e) for b, e, _m in spans] == [(0, 35), (200, 210)]
    # huge threshold: one span covering everything
    spans = plan_coalesced_spans(entries, 1 << 20)
    assert [(b, e) for b, e, _m in spans] == [(0, 210)]
    assert [m[2] for m in spans[0][2]] == [0, 1, 2, 3]
    # boundary case: gap exactly == threshold merges, threshold+1 doesn't
    two = [(0, 10, 0), (14, 10, 1)]
    assert len(plan_coalesced_spans(two, 4)) == 1
    assert len(plan_coalesced_spans(two, 3)) == 2
    assert plan_coalesced_spans([], 64) == []


def test_planner_contained_entry_extends_nothing():
    # an entry wholly inside its predecessor must not shrink the span
    # end (running-max semantics), and still shows up as a member
    entries = [(0, 100, 0), (10, 5, 1), (120, 10, 2)]
    spans = plan_coalesced_spans(entries, 30)
    assert [(b, e) for b, e, _m in spans] == [(0, 130)]
    assert [m[2] for m in spans[0][2]] == [0, 1, 2]


# -- mode semantics ----------------------------------------------------------
def test_window_covers_and_matches_sequential_multiset():
    records = records_of(53)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        seq = drain(IndexedRecordIOSplitter(p, idx, 0, 1, batch_size=7))
        s = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=7, shuffle="window", seed=11,
            window=16, merge_gap=32,
        )
        epoch = drain(s)
        s.close()
        assert sorted(epoch) == sorted(seq)  # nothing lost or duplicated
        assert epoch != seq  # actually shuffled


def test_window_order_is_deterministic_and_equals_record_mode():
    """The windowed machinery changes HOW bytes are read, never the
    emitted order: same (seed, epoch) → the exact shuffle='record'
    sequence, across window/merge_gap/readahead settings."""
    records = records_of(101)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        ref = drain(
            IndexedRecordIOSplitter(
                p, idx, 0, 1, batch_size=7, shuffle="record", seed=5
            )
        )
        for window, gap, ra in ((16, 0, True), (64, 1 << 20, True),
                                (7, 8, False), (1000, 64, True)):
            s = IndexedRecordIOSplitter(
                p, idx, 0, 1, batch_size=7, shuffle="window", seed=5,
                window=window, merge_gap=gap, readahead=ra,
            )
            assert drain(s) == ref, (window, gap, ra)
            s.close()


def test_window_before_first_rebuilds_each_epoch_exactly():
    records = records_of(60)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=8, shuffle="window", seed=3, window=16
        )
        e0 = drain(s)
        s.before_first()
        e1 = drain(s)
        s.close()
        assert e0 != e1  # reshuffled per epoch
        # a fresh splitter pinned to each epoch reproduces it exactly
        # (the resume-rebuild contract)
        for want, epoch in ((e0, 0), (e1, 1)):
            s2 = IndexedRecordIOSplitter(
                p, idx, 0, 1, batch_size=8, shuffle="window", seed=3,
                window=16, epoch=epoch,
            )
            assert drain(s2) == want, epoch
            s2.close()


def test_window_skip_records_resumes_at_window_boundaries():
    records = records_of(101)  # 6 full windows of 16 + a 5-record tail
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)

        def epoch(skip=0):
            s = IndexedRecordIOSplitter(
                p, idx, 0, 1, batch_size=7, shuffle="window", seed=9,
                window=16, epoch=1, skip_records=skip,
            )
            out = drain(s)
            consumed = s.records_consumed
            s.close()
            return out, consumed

        full, n = epoch()
        assert n == len(records)
        for k in (1, 3, 6):
            tail, consumed = epoch(skip=16 * k)
            assert tail == full[16 * k:], k
            assert consumed == len(records)  # skip counts as consumed
        # skipping everything (total is not a window multiple) is legal
        done, consumed = epoch(skip=len(records))
        assert done == []
        assert consumed == len(records)
        # inside a window: loud failure, not a silent replay/skip
        with pytest.raises(Error, match="window boundaries"):
            epoch(skip=16 * 2 + 3)


def test_window_sharding_exact_and_multifile_spans():
    records = records_of(75, tag="m")
    with TemporaryDirectory() as tmp:
        # two files, one global index (offsets are dataset-global), so
        # windows plan spans that cross the file boundary
        ra, rb = records[:40], records[40:]
        pa, _ = make_indexed_rec(tmp.path, ra, name="a")
        ms = MemoryStream()
        w = RecordIOWriter(ms)
        offs_b = []
        for r in rb:
            offs_b.append(ms.tell())
            w.write_record(r)
        pb = os.path.join(tmp.path, "b.rec")
        with open(pb, "wb") as f:
            f.write(ms.getvalue())
        size_a = os.path.getsize(pa)
        idx = os.path.join(tmp.path, "ab.idx")
        with open(idx, "w") as f:
            ms2 = MemoryStream()
            w2 = RecordIOWriter(ms2)
            for i, r in enumerate(ra):
                f.write(f"{i} {ms2.tell()}\n")
                w2.write_record(r)
            for i, off in enumerate(offs_b):
                f.write(f"{40 + i} {size_a + off}\n")
        uri = f"{pa};{pb}"
        got = []
        for rank in range(3):
            s = IndexedRecordIOSplitter(
                uri, idx, rank, 3, batch_size=7, shuffle="window",
                seed=2, window=8, merge_gap=1 << 20,
            )
            part = drain(s)
            s.close()
            got.extend(part)
        assert sorted(got) == sorted(records)


def test_window_io_stats_prove_coalescing_and_pread():
    records = records_of(90)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=9, shuffle="window", seed=4,
            window=1 << 20, merge_gap=1 << 20,  # one window, one span
        )
        assert sorted(drain(s)) == sorted(records)
        stats = s.io_stats()
        s.close()
        assert stats["mode"] == "window"
        assert stats["records"] == len(records)
        assert stats["spans"] == 1  # coalesced: spans << records
        assert stats["seeks"] == 0  # local pread fast path
        assert stats["bytes_read"] == os.path.getsize(p)
        # drain() re-frames bytes, so the emissions count as fallback
        # gather batches (the zero-copy counter stays 0)
        assert stats["gather_fallback_batches"] > 0
        assert stats["gather_batches"] == 0
        # record mode rides the same machinery now (ISSUE 6): one
        # shard-wide window, same coalesced shape
        g = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=9, shuffle="record", seed=4
        )
        drain(g)
        gstats = g.io_stats()
        g.close()
        assert gstats["spans"] == 1
        assert gstats["seeks"] == 0
        # the per-record reference shape survives behind the legacy
        # escape hatch (the A/B baseline for shuffled_gather_speedup)
        r = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=9, shuffle="record", seed=4,
            legacy_shuffle=True,
        )
        drain(r)
        rstats = r.io_stats()
        r.close()
        assert rstats["spans"] == len(records)
        assert rstats["seeks"] == len(records)


def test_window_uri_sugar_and_factory_wrapping():
    records = records_of(40)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = io_split.create(
            f"{p}?index={idx}&shuffle=window&window=8&merge_gap=4&seed=6",
            type="recordio",
        )
        # window mode prefetches internally: returned bare, not wrapped
        assert isinstance(s, IndexedRecordIOSplitter)
        assert s.shuffle_mode == "window"
        assert s.window == 8 and s.merge_gap == 4
        assert sorted(drain(s)) == sorted(records)
        s.close()
        with pytest.raises(Error, match="window=0 must be >= 1"):
            io_split.create(
                f"{p}?index={idx}&shuffle=window&window=0", type="recordio"
            )
        with pytest.raises(Error, match="not an integer"):
            io_split.create(
                f"{p}?index={idx}&shuffle=window&merge_gap=big",
                type="recordio",
            )
        with pytest.raises(Error, match="shuffle=.*window"):
            io_split.create(
                f"{p}?index={idx}&shuffle=windo", type="recordio"
            )


def test_window_mode_through_ell_batches_io_stats():
    """The fused staging fan-in surfaces the split's counters (the
    bench's proof hook) and stages the same multiset of rows."""
    np = pytest.importorskip("numpy")
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import encode_rows
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.staging import BatchSpec, ell_batches

    n, k = 64, 3
    rng = np.random.default_rng(1)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n).astype(np.float32),
        index=rng.integers(0, 50, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    with TemporaryDirectory() as tmp:
        rec = os.path.join(tmp.path, "t.rec")
        idx = os.path.join(tmp.path, "t.idx")
        with FileStream(rec, "w") as d, FileStream(idx, "w") as i:
            w = IndexedRecordIOWriter(d, i)
            for payload in encode_rows(blk):
                w.write_record(payload)
        spec = BatchSpec(batch_size=16, layout="ell", max_nnz=k)
        stream = ell_batches(
            f"{rec}?index={idx}&shuffle=window&window=16&seed=8", spec
        )
        labels = []
        for b in stream:
            labels.extend(np.asarray(b.labels)[: b.n_valid].tolist())
        stats = stream.io_stats()
        stream.close()
        assert sorted(labels) == list(range(n))  # coverage through ELL
        assert labels != list(range(n))  # shuffled
        assert stats is not None and stats["mode"] == "window"
        assert stats["spans"] < stats["records"]


def test_window_empty_shard_rank_and_reset_partition():
    records = records_of(10)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=3, shuffle="window", seed=1, window=4
        )
        assert s.next_record() is not None
        s.reset_partition(7, 8)  # 7*2 >= 10 → empty rank
        assert s.next_record() is None
        s.reset_partition(0, 2)  # back to a live rank: fresh pipeline
        assert len(drain(s)) == 5
        s.close()
