"""Windowed time series (telemetry/timeseries.py, ISSUE 14): the
per-process sample ring, the windowed-rate query (counter-reset
handling, histogram deltas), the tracker-side cluster store's
monotone-clock contract under worker relaunch, heartbeat transport of
ring samples, the ``/metrics.json?window=`` contract, and the ``tools
top --once --json`` smoke against an in-process tracker."""

import json
import time

import pytest

from dmlc_core_tpu.telemetry import MetricRegistry
from dmlc_core_tpu.telemetry import timeseries as ts


def _mk_registry():
    return MetricRegistry()


# -- windowed() pure query ----------------------------------------------------


def test_windowed_counter_rates_and_gauges():
    reg = _mk_registry()
    c = reg.counter("io.split.records")
    g = reg.gauge("tracker.shards.queue_depth")
    samples = []
    c.inc(100)
    g.set(7)
    s = ts.take_sample(reg, 1)
    s["t"] = 100.0
    samples.append(s)
    c.inc(300)
    g.set(3)
    s = ts.take_sample(reg, 2)
    s["t"] = 110.0
    samples.append(s)
    win = ts.windowed(samples, 30.0)
    assert win["samples"] == 2
    rec = win["counters"]["io.split.records"]
    assert rec["delta"] == 300.0
    assert rec["per_sec"] == pytest.approx(30.0)
    qd = win["gauges"]["tracker.shards.queue_depth"]
    assert qd["last"] == 3.0 and qd["max"] == 7.0 and qd["min"] == 3.0
    assert win["derived"]["rows_per_sec"] == pytest.approx(30.0)
    assert win["derived"]["shard_queue_depth"]["last"] == 3.0


def test_windowed_picks_baseline_at_window_edge():
    """The baseline is the newest sample AT/BEFORE the window start —
    a 10 s window over a 60 s series must rate the last 10 s only."""
    reg = _mk_registry()
    c = reg.counter("io.split.records")
    samples = []
    for i in range(7):
        c.inc(100 if i < 6 else 10_000)  # the last step is much hotter
        s = ts.take_sample(reg, i + 1)
        s["t"] = 100.0 + i * 10.0
        samples.append(s)
    win = ts.windowed(samples, 10.0)
    assert win["counters"]["io.split.records"]["delta"] == 10_000.0
    assert win["counters"]["io.split.records"]["per_sec"] == pytest.approx(
        1000.0
    )


def test_windowed_counter_reset_is_rate_since_restart():
    """A relaunched worker's counters restart at zero; the windowed
    delta must be the value-since-restart, never negative (Prometheus
    counter-reset semantics)."""
    samples = [
        {"t": 100.0, "seq": 1, "counters": {"io.split.records": 5000.0},
         "gauges": {}, "histograms": {}},
        {"t": 110.0, "seq": 2, "counters": {"io.split.records": 400.0},
         "gauges": {}, "histograms": {}},
    ]
    win = ts.windowed(samples, 60.0)
    assert win["counters"]["io.split.records"]["delta"] == 400.0
    assert win["counters"]["io.split.records"]["per_sec"] >= 0


def test_windowed_histogram_delta_percentiles():
    reg = _mk_registry()
    h = reg.histogram("io.lookup.request_seconds")
    for _ in range(100):
        h.observe(1e-3)
    s1 = ts.take_sample(reg, 1)
    s1["t"] = 100.0
    for _ in range(100):
        h.observe(0.5)  # the WINDOW is all-slow even if history is fast
    s2 = ts.take_sample(reg, 2)
    s2["t"] = 130.0
    win = ts.windowed([s1, s2], 60.0)
    d = win["histograms"]["io.lookup.request_seconds"]
    assert d["count"] == 100
    assert d["p50"] > 0.1  # the fast pre-window observations are gone


def test_windowed_histogram_mismatched_edges_degrade_to_head():
    """A baseline with foreign bucket edges (version skew, restart with
    different bounds) must not corrupt the delta — the head snapshot
    stands alone."""
    base = {"t": 100.0, "seq": 1, "counters": {}, "gauges": {},
            "histograms": {"h": {"le": [1.0, 2.0], "n": [1, 1, 0],
                                 "count": 2, "sum": 2.0}}}
    head = {"t": 110.0, "seq": 2, "counters": {}, "gauges": {},
            "histograms": {"h": {"le": [1.0, 4.0], "n": [3, 1, 0],
                                 "count": 4, "sum": 5.0}}}
    win = ts.windowed([base, head], 60.0)
    assert win["histograms"]["h"]["count"] == 4  # head, not a bad delta


def test_stall_fraction_derived_from_trace_mirror():
    samples = []
    for i, stall in enumerate((0.0, 6.0)):
        samples.append({
            "t": 100.0 + i * 10.0, "seq": i + 1,
            "counters": {
                'trace.stall_seconds{stage="shard_lease_wait"}': stall,
                "io.split.records": 100.0 * (i + 1),
            },
            "gauges": {}, "histograms": {},
        })
    win = ts.windowed(samples, 60.0)
    assert win["derived"]["stall_fraction"]["shard_lease_wait"] == (
        pytest.approx(0.6)
    )


# -- TimeSeriesRing ------------------------------------------------------------


def test_ring_incremental_samples_and_retention():
    reg = _mk_registry()
    ring = ts.TimeSeriesRing(registry=reg, interval=0.05, retention=3600)
    for _ in range(5):
        ring.sample()
    assert [s["seq"] for s in ring.samples(since=3)] == [4, 5]
    assert ring.last_seq == 5
    # retention: a tiny window evicts all but the newest tail
    tight = ts.TimeSeriesRing(registry=reg, interval=0.05, retention=0.05)
    tight.sample()
    time.sleep(0.12)
    tight.sample()
    assert len(tight.samples()) == 1  # the stale head fell out


def test_ring_sampler_thread_samples_on_interval():
    reg = _mk_registry()
    ring = ts.TimeSeriesRing(registry=reg, interval=0.05, retention=60)
    ring.start()
    try:
        time.sleep(0.4)
        assert len(ring.samples()) >= 3
    finally:
        ring.stop()


# -- ClusterTimeSeries ---------------------------------------------------------


def test_cluster_store_clock_never_goes_backwards():
    """A relaunched rank re-shipping its dead predecessor's tail (or a
    skewed clock) must be dropped, not splice the series backwards —
    the satellite's restart contract."""
    store = ts.ClusterTimeSeries(retention=3600)
    ok = store.add(0, [
        {"t": 100.0, "seq": 1, "counters": {"c": 1.0}, "gauges": {},
         "histograms": {}},
        {"t": 102.0, "seq": 2, "counters": {"c": 2.0}, "gauges": {},
         "histograms": {}},
    ])
    assert ok == 2
    # the relaunch: seq restarts, counters restart, and the FIRST
    # sample replays a stale timestamp
    ok = store.add(0, [
        {"t": 101.0, "seq": 1, "counters": {"c": 0.5}, "gauges": {},
         "histograms": {}},   # stale: dropped
        {"t": 104.0, "seq": 2, "counters": {"c": 3.0}, "gauges": {},
         "histograms": {}},   # fresh: accepted
    ])
    assert ok == 1
    assert store.dropped_stale == 1
    view = store.window(60.0)["per_rank"]["0"]
    assert view["samples"] == 3, view
    # and the reset counter still rates non-negatively
    assert view["counters"]["c"]["delta"] >= 0


def test_cluster_store_rejects_malformed_samples():
    store = ts.ClusterTimeSeries()
    assert store.add(1, "nonsense") == 0
    assert store.add(1, [{"t": "soon"}, {"no_t": 1}, 42]) == 0
    assert store.ranks() == [1]


def test_dsserve_data_plane_derivations():
    """The dashboard's data-plane signals: wire ratio = wire/raw byte
    rates (codec win when < 1), shm fraction = shm/(shm+tcp) slots."""
    samples = []
    for i, (w, r, shm, tcp) in enumerate(
        ((0.0, 0.0, 0.0, 0.0), (50.0, 100.0, 3.0, 1.0))
    ):
        samples.append({
            "t": 100.0 + i * 10.0, "seq": i + 1,
            "counters": {
                "dsserve.bytes_wire": w, "dsserve.bytes_raw": r,
                "dsserve.shm_slots": shm, "dsserve.tcp_slots": tcp,
            },
            "gauges": {}, "histograms": {},
        })
    win = ts.windowed(samples, 60.0)
    assert win["derived"]["dsserve_wire_ratio"] == pytest.approx(0.5)
    assert win["derived"]["dsserve_shm_frac"] == pytest.approx(0.75)


def test_merge_windows_averages_data_plane_fracs():
    """Wire ratio and shm fraction are per-process fractions: the
    cluster view averages them over reporting ranks (summing would read
    as nonsense, the stall-fraction rule)."""
    views = {
        str(i): {
            "samples": 2, "counters": {}, "gauges": {},
            "derived": {
                "rows_per_sec": 1.0,
                "dsserve_wire_ratio": ratio,
                "dsserve_shm_frac": frac,
            },
        }
        for i, (ratio, frac) in enumerate(((0.4, 1.0), (0.6, 0.5)))
    }
    merged = ts.merge_windows(views)
    assert merged["derived"]["dsserve_wire_ratio"] == pytest.approx(0.5)
    assert merged["derived"]["dsserve_shm_frac"] == pytest.approx(0.75)


def test_merge_windows_sums_rows_and_averages_fractions():
    views = {
        "0": {"samples": 2, "counters": {"io.split.records":
                                         {"delta": 10, "per_sec": 1.0}},
              "gauges": {},
              "derived": {"rows_per_sec": 100.0,
                          "stall_fraction": {"fetch_wait": 0.2}}},
        "1": {"samples": 2, "counters": {"io.split.records":
                                         {"delta": 30, "per_sec": 3.0}},
              "gauges": {},
              "derived": {"rows_per_sec": 300.0,
                          "stall_fraction": {"fetch_wait": 0.4}}},
    }
    merged = ts.merge_windows(views)
    assert merged["n_ranks"] == 2
    assert merged["derived"]["rows_per_sec"] == 400.0
    assert merged["derived"]["stall_fraction"]["fetch_wait"] == (
        pytest.approx(0.3)
    )
    assert merged["counters"]["io.split.records"]["per_sec"] == 4.0


# -- heartbeat transport + the /metrics.json?window= contract ------------------


def _start_tracker(n_workers=1):
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    tr = RabitTracker(host_ip="127.0.0.1", n_workers=n_workers)
    tr.start(n_workers)
    return tr


def test_heartbeat_ships_samples_and_window_endpoint(monkeypatch):
    """End-to-end: worker ring samples ride cmd=metrics; the tracker's
    /metrics.json?window=N answers nonzero per-rank windowed rows/s;
    the end-of-job report embeds the full series; the heartbeat RTT
    reply yields a clock-offset estimate for the trace otherData."""
    monkeypatch.setenv("DMLC_TS_INTERVAL", "0.1")
    from dmlc_core_tpu.io import retry
    from dmlc_core_tpu.telemetry import default_registry, tracing
    from dmlc_core_tpu.tracker.client import RabitWorker

    tracing.reset()
    tr = _start_tracker(1)
    try:
        w = RabitWorker(
            tracker_uri="127.0.0.1", tracker_port=tr.port, jobid="0"
        )
        w.start(1)
        c = default_registry().counter("io.split.records")
        for _ in range(4):
            c.inc(500)
            time.sleep(0.12)
        w.heartbeat()
        url = (
            f"http://127.0.0.1:{tr.metrics_port}/metrics.json?window=30"
        )
        with retry.request(url) as resp:
            rep = json.loads(resp.read().decode())
        win = rep["windowed"]
        assert win["window_secs"] == 30.0
        rank0 = win["per_rank"]["0"]
        assert rank0["samples"] >= 2
        assert rank0["derived"]["rows_per_sec"] > 0
        assert win["cluster"]["derived"]["rows_per_sec"] > 0
        # the tracker's own registry rides the "tracker" pseudo-rank
        assert "tracker" in win["per_rank"]
        # windowed polls are LIGHT: the heavy full series stays off
        # them (a dashboard refresh must not re-download minutes of
        # snapshots) and is served by the plain report instead
        assert "timeseries" not in rep
        full_url = f"http://127.0.0.1:{tr.metrics_port}/metrics.json"
        with retry.request(full_url) as resp:
            full = json.loads(resp.read().decode())
        assert full["timeseries"]["per_rank"]["0"]
        # the RTT midpoint produced a clock-offset estimate
        assert tracing.clock_offset_ns() is not None
        # a second heartbeat ships only NEW samples (incremental seq)
        first_total = len(full["timeseries"]["per_rank"]["0"])
        time.sleep(0.15)
        w.heartbeat()
        with retry.request(full_url) as resp:
            full2 = json.loads(resp.read().decode())
        assert len(full2["timeseries"]["per_rank"]["0"]) > first_total
        w.shutdown()
        tr.join()
    finally:
        tr.close()
        tracing.reset()


def test_tools_top_once_json_against_in_process_tracker(monkeypatch, capsys):
    """The tier-1 smoke the satellite asks for: ``tools top --once
    --json`` against a live in-process tracker reports per-rank rows/s
    within 10% of the value computed from the shipped samples."""
    monkeypatch.setenv("DMLC_TS_INTERVAL", "0.1")
    from dmlc_core_tpu import tools
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    tr = _start_tracker(1)
    try:
        # hand-crafted heartbeat payload: a precise 1000 rows/s series
        samples = [
            {"t": 1000.0 + i, "seq": i + 1,
             "counters": {"io.split.records": 1000.0 * (i + 1)},
             "gauges": {}, "histograms": {}}
            for i in range(5)
        ]
        tr.metrics.update(0, {"counters": {}, "gauges": {},
                              "histograms": {}, "timeseries": samples})
        rc = tools.main([
            "top", str(tr.metrics_port), "--once", "--json",
            "--window", "30",
        ])
        assert rc == 0
        model = json.loads(capsys.readouterr().out)
        got = model["ranks"]["0"]["rows_per_sec"]
        assert abs(got - 1000.0) / 1000.0 < 0.10, got
        assert model["cluster"]["rows_per_sec"] == pytest.approx(
            got
        )
        # the human rendering works off the same model
        rc = tools.main([
            "top", str(tr.metrics_port), "--once", "--window", "30",
        ])
        out = capsys.readouterr().out
        assert rc == 0 and "rows/s" in out and "dmlc top" in out
    finally:
        tr.close()


def test_top_model_pure():
    from dmlc_core_tpu.tools import _top_model

    report = {
        "windowed": {
            "per_rank": {
                "0": {"samples": 3, "derived": {
                    "rows_per_sec": 10.0,
                    "stall_fraction": {"fetch_wait": 0.5},
                    "lookup_qps": 12.0, "lookup_p99_ms": 4.0}},
                "tracker": {"samples": 3, "derived": {},
                            "gauges": {"tracker.shards.queue_depth":
                                       {"last": 5, "min": 1, "max": 9}}},
            },
            "cluster": {"n_ranks": 1,
                        "derived": {"rows_per_sec": 10.0,
                                    "stall_fraction": {}}},
        }
    }
    model = _top_model(report, 30.0)
    assert model["ranks"]["0"]["rows_per_sec"] == 10.0
    assert model["ranks"]["0"]["lookup_qps"] == 12.0
    assert model["shard_queue_depth"]["last"] == 5
    assert model["n_ranks"] == 1


def test_sampling_enabled_knob(monkeypatch):
    assert ts.sampling_enabled()
    monkeypatch.setenv("DMLC_TS", "off")
    assert not ts.sampling_enabled()
    monkeypatch.setenv("DMLC_TS", "1")
    assert ts.sampling_enabled()


# -- THE dmlc-submit acceptance ------------------------------------------------

_SUBMIT_WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.tracker.client import RabitWorker
from dmlc_core_tpu.io import split as io_split
w = RabitWorker()
rank = w.start()
sp = io_split.create(
    {rec!r} + "?index=" + {idx!r}
    + "&shuffle=record&window=128&dynamic_shards=1",
    type="recordio", threaded=False)
rows = 0
while True:
    g = sp.next_gather_batch(32)
    if g is None:
        break
    rows += len(g[1])
    time.sleep(0.01)  # pace the drain across a few sample intervals
sp.close()
w.heartbeat()  # ships the ring's samples + estimates the clock offset
w.shutdown()
"""


@pytest.mark.blockcache
def test_submit_run_windowed_rates_and_lease_flow_arrows(tmp_path):
    """ISSUE 14 acceptance: a 2-worker ``dmlc-submit`` run (block cache
    + dynamic shards) yields (a) an end-of-job report whose per-rank
    time series window to NONZERO rows/s and a shard_lease_wait stall
    fraction, and (b) a merged trace where every ``shard_lease_wait``
    span has a flow event binding it to the tracker's server-side
    ``shard_lease`` handler span."""
    import os
    import subprocess
    import sys

    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.telemetry import tracing

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rec = str(tmp_path / "corpus.rec")
    idx = rec + ".idx"
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(f, fi, codec="zlib", block_bytes=2048)
        for i in range(400):
            w.write_record(f"row-{i:06d}|".encode() * 8)
        w.flush_block()
    trace_dir = tmp_path / "traces"
    report_path = tmp_path / "metrics_report.json"
    script = tmp_path / "worker.py"
    script.write_text(_SUBMIT_WORKER.format(repo=REPO, rec=rec, idx=idx))
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.tracker.submit",
         "--cluster", "local", "--num-workers", "2",
         "--host-ip", "127.0.0.1", "--block-cache",
         "--trace-dir", str(trace_dir),
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=150,
        env={**os.environ, "DMLC_TRACE": "on", "JAX_PLATFORMS": "cpu",
             "DMLC_TS_INTERVAL": "0.1",
             "DMLC_METRICS_REPORT": str(report_path)},
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-3000:]

    # (a) the report's per-rank series windows to nonzero rates
    report = json.loads(report_path.read_text())
    per_rank = report["timeseries"]["per_rank"]
    assert "0" in per_rank and "1" in per_rank, sorted(per_rank)
    for rank in ("0", "1"):
        win = ts.windowed(per_rank[rank], 60.0)
        assert win["samples"] >= 2, (rank, win)
        assert win["derived"]["rows_per_sec"] > 0, (rank, win)
        # the lease RPCs ran under the stall span -> nonzero fraction
        assert win["derived"]["stall_fraction"].get(
            "shard_lease_wait", 0
        ) > 0, (rank, win["derived"])

    # (b) merged trace: every shard_lease_wait span carries its arrow
    files = sorted(
        str(trace_dir / f)
        for f in os.listdir(trace_dir)
        if f.startswith("dmlc-trace-")
    )
    assert len(files) >= 3, files  # 2 workers + tracker (+ daemon)
    merged = tracing.merge_traces(files)
    evs = merged["traceEvents"]
    waits = [
        e for e in evs
        if e["ph"] == "X" and e["name"] == "dmlc:shard_lease_wait"
    ]
    assert waits, "no shard_lease_wait spans on the merged timeline"
    handlers = [
        e for e in evs
        if e["ph"] == "X" and e["name"] == "dmlc:tracker_shard_lease"
    ]
    assert handlers, "tracker recorded no shard_lease handler spans"
    flows_s = [e for e in evs if e["ph"] == "s"]
    flows_f = {e["id"]: e for e in evs if e["ph"] == "f"}
    for w in waits:
        enclosed = [
            s for s in flows_s
            if s["pid"] == w["pid"] and s["tid"] == w["tid"]
            and w["ts"] <= s["ts"] <= w["ts"] + w["dur"]
        ]
        assert enclosed, f"shard_lease_wait at ts={w['ts']} has no flow"
        landed = [
            flows_f[s["id"]] for s in enclosed if s["id"] in flows_f
        ]
        assert landed, "lease flow never landed in the tracker"
        hit = any(
            h["pid"] == f["pid"] and h["tid"] == f["tid"]
            and h["ts"] <= f["ts"] <= h["ts"] + h["dur"]
            for f in landed
            for h in handlers
        )
        assert hit, "flow-finish outside every shard_lease handler span"

    # workers measured a clock offset off the heartbeat RTT reply
    offsets = [
        p.get("clock_offset_ns")
        for p in merged["otherData"]["processes"]
        if str(p.get("label", "")).startswith("worker")
    ]
    assert any(o is not None for o in offsets), offsets
