"""Data layer tests: RowBlock, parsers, row iterators.

Modeled on the reference test strategy (SURVEY §4): synthesized files in
temp dirs, rank-parameterized in-process "distributed" sharding asserts
(reference unittest_inputsplit.cc:116-145), and parser grammar cases
(reference unittest_parser.cc).
"""

import os

import numpy as np
import pytest

from dmlc_core_tpu import data as D
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_core_tpu.io.stream import MemoryStream


# -- RowBlock core -----------------------------------------------------------

def make_block(nrows=5, width=4, seed=0):
    rng = np.random.default_rng(seed)
    sizes = rng.integers(1, width + 1, size=nrows)
    offset = np.zeros(nrows + 1, dtype=np.int64)
    np.cumsum(sizes, out=offset[1:])
    nnz = int(offset[-1])
    return RowBlock(
        offset=offset,
        label=rng.normal(size=nrows).astype(np.float32),
        index=rng.integers(0, 100, size=nnz).astype(np.uint64),
        value=rng.normal(size=nnz).astype(np.float32),
    )


def test_row_block_accessors():
    blk = make_block()
    assert blk.size == 5
    rows = list(blk)
    assert len(rows) == 5
    total = sum(len(r) for r in rows)
    assert total == blk.nnz
    w = np.arange(100, dtype=np.float32)
    r = blk[0]
    manual = sum(w[int(i)] * v for i, v in zip(r.index, r.value))
    assert abs(r.sdot(w) - manual) < 1e-4


def test_row_block_slice_rebased():
    blk = make_block(10)
    s = blk.slice(3, 7)
    assert s.size == 4
    assert s.offset[0] == 0
    for i in range(4):
        orig, sub = blk[3 + i], s[i]
        np.testing.assert_array_equal(orig.index, sub.index)
        np.testing.assert_array_equal(orig.value, sub.value)
        assert orig.label == sub.label


def test_row_block_save_load_roundtrip():
    blk = make_block(7)
    ms = MemoryStream()
    blk.save(ms)
    ms.seek(0)
    back = RowBlock.load(ms)
    np.testing.assert_array_equal(blk.offset, back.offset)
    np.testing.assert_array_equal(blk.index, back.index)
    np.testing.assert_array_equal(blk.value, back.value)
    np.testing.assert_array_equal(blk.label, back.label)
    assert RowBlock.load(ms) is None  # clean EOF


def test_container_push_rows_and_blocks():
    c = RowBlockContainer()
    c.push_row(1.0, [3, 5], [0.5, 2.0])
    c.push_row(0.0, [1], None)
    c.push_block(make_block(3))
    blk = c.to_block()
    assert blk.size == 5
    assert blk[0].label == 1.0
    assert blk[1].get_value(0) == 1.0  # missing value defaults to 1
    assert c.max_index >= 5


def test_concat_mixed_value_presence():
    a = RowBlock(
        offset=np.array([0, 2]), label=np.array([1.0], np.float32),
        index=np.array([0, 1], np.uint64), value=np.array([2.0, 3.0], np.float32),
    )
    b = RowBlock(
        offset=np.array([0, 1]), label=np.array([0.0], np.float32),
        index=np.array([4], np.uint64), value=None,
    )
    cat = RowBlock.concat([a, b])
    assert cat.size == 2
    assert cat.value is not None
    assert cat.value[2] == 1.0  # filled default


# -- parsers -----------------------------------------------------------------

LIBSVM_TEXT = b"""1 0:1.5 3:2.5 # a comment
-1 1:0.5
# full comment line

0.5:2.0 qid:7 2:1.0 4:4.0
"""


def write_parse(tmp_path, name, text, fmt, args=""):
    path = tmp_path / name
    with open(path, "wb") as f:
        f.write(text)
    uri = f"{path}?{args}" if args else str(path)
    parser = D.create_parser(uri, type=fmt, threaded=False)
    blocks = []
    while True:
        got = parser.parse_next()
        if got is None:
            break
        blocks.extend(b for b in got if b.size)
    parser.close()
    return RowBlock.concat(blocks) if blocks else None


def test_libsvm_grammar(tmp_path):
    blk = write_parse(tmp_path, "a.libsvm", LIBSVM_TEXT, "libsvm")
    assert blk.size == 3
    np.testing.assert_allclose(blk.label, [1.0, -1.0, 0.5])
    # row 0: two features with values
    np.testing.assert_array_equal(blk[0].index, [0, 3])
    np.testing.assert_allclose(blk[0].value, [1.5, 2.5])
    # row 2: weight + qid
    assert blk.weight is not None and blk.weight[2] == 2.0
    assert blk.qid is not None and blk.qid[2] == 7
    assert blk.qid[0] == 0


def test_libsvm_binary_features_no_values(tmp_path):
    blk = write_parse(tmp_path, "b.libsvm", b"1 3 5 9\n0 2 4\n", "libsvm")
    assert blk.size == 2
    assert blk.value is None
    np.testing.assert_array_equal(blk[0].index, [3, 5, 9])
    assert blk[0].get_value(1) == 1.0


def test_libsvm_indexing_modes(tmp_path):
    text = b"1 1:0.5 3:0.5\n0 2:1.0\n"
    forced = write_parse(tmp_path, "c.libsvm", text, "libsvm", "indexing_mode=1")
    assert int(forced.index.min()) == 0
    auto = write_parse(tmp_path, "d.libsvm", text, "libsvm", "indexing_mode=-1")
    assert int(auto.index.min()) == 0  # heuristic: all ids > 0 → 1-based
    keep = write_parse(tmp_path, "e.libsvm", text, "libsvm", "indexing_mode=0")
    assert int(keep.index.min()) == 1


def test_csv_basic(tmp_path):
    text = b"1.0,2.0,3.0\n4.0,5.0,6.0\n"
    blk = write_parse(tmp_path, "a.csv", text, "csv")
    assert blk.size == 2
    np.testing.assert_allclose(blk.label, [0.0, 0.0])  # no label column
    np.testing.assert_allclose(blk[1].value, [4.0, 5.0, 6.0])
    np.testing.assert_array_equal(blk[0].index, [0, 1, 2])


def test_csv_label_weight_columns(tmp_path):
    text = b"7.0,1.0,0.25,2.0\n8.0,3.0,0.5,4.0\n"
    blk = write_parse(
        tmp_path, "b.csv", text, "csv", "label_column=0&weight_column=2"
    )
    np.testing.assert_allclose(blk.label, [7.0, 8.0])
    np.testing.assert_allclose(blk.weight, [0.25, 0.5])
    np.testing.assert_allclose(blk[0].value, [1.0, 2.0])


def test_csv_delimiter_and_int_dtype(tmp_path):
    text = b"1\t2\t3\n4\t5\t6\n"
    blk = write_parse(
        tmp_path, "c.csv", text, "csv", "delimiter=%s&dtype=int64" % "\t"
    )
    assert blk.value.dtype == np.int64
    np.testing.assert_array_equal(blk[0].value, [1, 2, 3])


def test_csv_empty_fields_are_zero(tmp_path):
    blk = write_parse(tmp_path, "d.csv", b"1.0,,3.0\n", "csv")
    np.testing.assert_allclose(blk[0].value, [1.0, 0.0, 3.0])


def test_libfm_grammar(tmp_path):
    text = b"1 0:3:1.5 2:7:0.5\n-1:0.5 1:4:2.0\n"
    blk = write_parse(tmp_path, "a.libfm", text, "libfm")
    assert blk.size == 2
    assert blk.field is not None
    np.testing.assert_array_equal(blk[0].field, [0, 2])
    np.testing.assert_array_equal(blk[0].index, [3, 7])
    np.testing.assert_allclose(blk[0].value, [1.5, 0.5])
    assert blk.weight is not None and blk.weight[1] == 0.5


def test_libfm_indexing_auto(tmp_path):
    text = b"1 1:1:0.5 2:3:0.5\n"
    blk = write_parse(tmp_path, "b.libfm", text, "libfm", "indexing_mode=-1")
    np.testing.assert_array_equal(blk[0].field, [0, 1])
    np.testing.assert_array_equal(blk[0].index, [0, 2])


def test_format_auto_detect_from_uri(tmp_path):
    path = tmp_path / "data.txt"
    with open(path, "wb") as f:
        f.write(b"1.0,2.0\n")
    it = D.create_row_block_iter(f"{path}?format=csv&label_column=0")
    blk = it.next()
    assert blk.size == 1
    np.testing.assert_allclose(blk.label, [1.0])
    assert it.next() is None


# -- distributed sharding (reference unittest_inputsplit.cc:116-145) ---------

def test_split_libsvm_distributed(tmp_path):
    """5 files × 2 rows read as 2 parts: every row lands in exactly one
    part, record-aligned."""
    n_files, rows_per_file = 5, 2
    uris = []
    row_id = 0
    for i in range(n_files):
        p = tmp_path / f"part{i}.libsvm"
        with open(p, "wb") as f:
            for _ in range(rows_per_file):
                f.write(b"%d 0:1 %d:2\n" % (row_id, row_id + 1))
                row_id += 1
        uris.append(str(p))
    uri = ";".join(uris)
    seen = []
    total = 0
    for rank in range(2):
        parser = D.create_parser(uri, rank, 2, type="libsvm", threaded=False)
        labels = []
        for blk in parser:
            labels.extend(blk.label.astype(int).tolist())
        parser.close()
        total += len(labels)
        seen.extend(labels)
    assert total == n_files * rows_per_file
    assert sorted(seen) == list(range(n_files * rows_per_file))


def test_threaded_parser_matches_plain(tmp_path):
    rng = np.random.default_rng(42)
    p = tmp_path / "big.libsvm"
    with open(p, "wb") as f:
        for i in range(2000):
            feats = " ".join(
                f"{j}:{rng.normal():.4f}" for j in sorted(rng.integers(0, 50, 5))
            )
            f.write(f"{i % 2} {feats}\n".encode())
    plain = D.create_parser(str(p), threaded=False)
    threaded = D.create_parser(str(p), threaded=True)
    a = RowBlock.concat(list(plain))
    b = RowBlock.concat(list(threaded))
    plain.close()
    threaded.close()
    assert a.size == b.size == 2000
    np.testing.assert_array_equal(a.offset, b.offset)
    np.testing.assert_array_equal(a.index, b.index)
    np.testing.assert_allclose(a.value, b.value)


# -- row iterators -----------------------------------------------------------

def test_basic_row_iter(tmp_path):
    p = tmp_path / "x.libsvm"
    with open(p, "wb") as f:
        f.write(b"1 0:1 9:2\n0 4:1\n")
    it = D.create_row_block_iter(str(p), type="libsvm")
    assert it.num_col() == 10
    blk = it.next()
    assert blk.size == 2
    assert it.next() is None
    it.before_first()
    assert it.next().size == 2


def test_disk_row_iter_cache_epochs(tmp_path):
    p = tmp_path / "x.libsvm"
    cache = tmp_path / "x.cache"
    with open(p, "wb") as f:
        for i in range(100):
            f.write(b"%d %d:1.0\n" % (i % 2, i % 7))
    it = D.create_row_block_iter(f"{p}#{cache}", type="libsvm")
    assert os.path.exists(cache)
    rows1 = sum(b.size for b in it)
    it.before_first()
    rows2 = sum(b.size for b in it)
    assert rows1 == rows2 == 100
    assert it.num_col() == 7
    it.close()
    # second iterator reuses the cache file
    it2 = D.create_row_block_iter(f"{p}#{cache}", type="libsvm")
    assert sum(b.size for b in it2) == 100
    it2.close()


def test_parser_registry_unknown_type(tmp_path):
    p = tmp_path / "x.libsvm"
    p.write_text("1 0:1\n")
    with pytest.raises(Exception, match="Unknown data type"):
        D.create_parser(str(p), type="nope")


# -- regressions from review -------------------------------------------------

def test_csv_single_column_accepted(tmp_path):
    """Reference fatals only when a line yields NO feature (csv_parser.h:123)."""
    blk = write_parse(tmp_path, "one.csv", b"1\n2\n3\n", "csv")
    assert blk.size == 3
    np.testing.assert_allclose(blk[0].value, [1.0])


def test_csv_int_dtype_prefix_parse(tmp_path):
    """strtoll(base 0) prefix semantics: '1.9'→1, '010'→8, '123abc'→123."""
    blk = write_parse(
        tmp_path, "pfx.csv", b"1.9,010,123abc,-7\n", "csv", "dtype=int64"
    )
    np.testing.assert_array_equal(blk[0].value, [1, 8, 123, -7])


def test_libsvm_malformed_qid_tolerated(tmp_path):
    blk = write_parse(tmp_path, "q.libsvm", b"1 qid:abc 1:0.5\n", "libsvm")
    assert blk.size == 1
    assert blk.qid[0] == 0
    np.testing.assert_array_equal(blk[0].index, [1])


def test_row_block_rejects_mismatched_arrays():
    with pytest.raises(Exception, match="value size mismatch"):
        RowBlock(
            offset=np.array([0, 2]), label=np.array([1.0], np.float32),
            index=np.array([0, 1], np.uint64),
            value=np.array([0.5], np.float32),
        )
    c = RowBlockContainer()
    with pytest.raises(Exception, match="length mismatch"):
        c.push_row(1.0, [1, 2], value=[0.5])


def test_threaded_iter_before_first_raises_pending_error():
    from dmlc_core_tpu.concurrency.threaded_iter import ThreadedIter

    calls = []

    def producer():
        calls.append(1)
        yield 1
        raise RuntimeError("transient failure")

    it = ThreadedIter(producer, max_capacity=2)
    assert it.next() == 1
    import time
    time.sleep(0.1)  # let the producer hit the failure
    with pytest.raises(RuntimeError, match="transient failure"):
        it.before_first()


def test_default_parser_threads_tpu_host_policy(monkeypatch):
    """TPU-host divergence: no procs//2-4 throttle; env var overrides.
    Sizing derives from the AVAILABLE (affinity/quota-aware) cpu count,
    not the raw host count (utils/cpus.py)."""
    from dmlc_core_tpu.data.text_parser import default_parser_threads

    monkeypatch.delenv("DMLC_PARSE_THREADS", raising=False)
    monkeypatch.setattr(
        "dmlc_core_tpu.utils.cpus.available_cpus", lambda: 8
    )
    assert default_parser_threads(None) == 8  # all usable cores by default
    assert default_parser_threads(16) == 8  # capped at usable count
    assert default_parser_threads(3) == 3
    monkeypatch.setenv("DMLC_TPU_PARSER_THREADS", "5")  # legacy alias
    assert default_parser_threads(None) == 5
    assert default_parser_threads(2) == 5  # env wins
    monkeypatch.setenv("DMLC_PARSE_THREADS", "7")  # documented knob wins
    assert default_parser_threads(None) == 7


def test_threaded_parser_bytes_read_is_delivery_watermark():
    """ISSUE 10 satellite: bytes_read() must report bytes behind
    DELIVERED batches, not race the producer thread mid-chunk. A base
    parser whose counter jumps before its batch crosses the queue
    exposes the over-report: after pulling batch k, the wrapper must
    answer batch k's watermark exactly."""
    import threading

    from dmlc_core_tpu.data.parser import Parser, ThreadedParser

    produced = threading.Semaphore(0)

    class StepParser(Parser):
        """Each parse_next 'consumes' 100 bytes and emits one block."""

        def __init__(self):
            self.n = 0

        def parse_next(self):
            if self.n >= 5:
                return None
            self.n += 1
            produced.release()
            return [make_block(2, seed=self.n)]

        def before_first(self):
            self.n = 0

        def bytes_read(self):
            return self.n * 100

        def close(self):
            pass

    tp = ThreadedParser(StepParser(), max_capacity=8)
    # let the producer run ahead: its own bytes_read() races to 500
    # while nothing was delivered yet
    for _ in range(5):
        produced.acquire(timeout=5)
    assert tp.bytes_read() == 0  # nothing delivered → nothing counted
    seen = 0
    while True:
        blocks = tp.parse_next()
        if blocks is None:
            break
        seen += 1
        # exact watermark at every batch boundary, never ahead
        assert tp.bytes_read() == seen * 100
    assert seen == 5 and tp.bytes_read() == 500
    # rewind resets the watermark with the stream
    tp.before_first()
    assert tp.bytes_read() == 0
    assert tp.parse_next() is not None
    assert tp.bytes_read() == 100
    tp.close()


def test_text_parser_close_waits_for_inflight_workers(tmp_path):
    """ISSUE 10 satellite: close() must not tear the source down while
    parse_block futures still run — cancel the pending, wait for the
    running, THEN close the split."""
    import threading
    import time as _time

    from dmlc_core_tpu.data.text_parser import TextParserBase
    from dmlc_core_tpu.io import split as io_split

    p = tmp_path / "t.txt"
    p.write_text("".join(f"{i}\n" for i in range(20000)))

    entered = threading.Event()
    release = threading.Event()
    closed_during_parse = []

    class SlowParser(TextParserBase):
        def parse_block(self, data):
            entered.set()
            release.wait(timeout=10)
            # the source must still be open while this worker runs
            closed_during_parse.append(self.source_closed())
            return make_block(1)

        def source_closed(self):
            src = self.source
            base = getattr(src, "_base", src)
            return getattr(base, "_fs", None) is None and (
                getattr(base, "offset_begin", 1)
                < getattr(base, "offset_end", 0)
            )

    src = io_split.create(str(p), type="text", threaded=False)
    tp = SlowParser(src, nthread=4)
    if tp._pool is None:  # 1-cpu box: fan-out disabled, nothing to race
        tp.close()
        return

    def pull():
        try:
            tp.parse_next()
        except Exception:
            # a PENDING slice cancelled by close() surfaces here as
            # CancelledError — expected when closing under a live pull
            pass

    puller = threading.Thread(target=pull, daemon=True)
    puller.start()
    assert entered.wait(timeout=10)
    closer = threading.Thread(target=tp.close, daemon=True)
    closer.start()
    _time.sleep(0.2)  # close() must now be BLOCKED on the running worker
    assert closer.is_alive(), "close() returned with a worker in flight"
    release.set()
    closer.join(timeout=10)
    assert not closer.is_alive()
    puller.join(timeout=10)
    # no RUNNING worker ever observed a closed source
    assert closed_during_parse and not any(closed_during_parse)
