"""Fused libsvm→ELL kernel parity: native/fastparse.cc
dmlc_parse_libsvm_ell vs LibSVMParser → FixedShapeBatcher('ell') composed
(reference premier text hot path, src/data/libsvm_parser.h:86-169). The
fused and generic batch streams must agree bit-for-bit on labels/weights/
indices/values/nnz/truncation across dtypes, indexing modes, comments,
qid tokens, junk, and sharding."""

import numpy as np
import pytest

from dmlc_core_tpu.data import create_parser, native
from dmlc_core_tpu.staging import BatchSpec, FixedShapeBatcher, ell_batches

fused = pytest.mark.skipif(
    not native.HAS_LIBSVM_ELL,
    reason="native fused libsvm ELL kernel not built",
)


def _write_libsvm(path, rows=400, k_max=6, one_based=False, seed=0,
                  junk=False, qid=False, comments=False):
    rng = np.random.default_rng(seed)
    lo = 1 if one_based else 0
    lines = []
    for i in range(rows):
        k = int(rng.integers(1, k_max + 1))
        toks = [f"{i % 2}" if i % 3 else f"{i % 2}:{0.5 + (i % 5)}"]
        if qid and i % 2 == 0:
            toks.append(f"qid:{i}")
        for _ in range(k):
            feat = int(rng.integers(lo, 5000))
            if rng.random() < 0.6:
                toks.append(f"{feat}:{rng.normal():.4f}")
            else:
                toks.append(f"{feat}")  # bare index: value 1.0
        if junk and i % 7 == 0:
            toks.append("noise")       # junk word: skipped
            toks.append("a:b")         # malformed numbers: skipped
            toks.append(":")           # empty halves: skipped
        line = " ".join(toks)
        if comments and i % 5 == 0:
            line += " # trailing comment 9:9"
        lines.append(line)
    if junk:
        lines.insert(5, "not_a_label 1:2")  # bad label: line skipped
        lines.insert(9, "")                  # blank line
    if comments:
        lines.insert(3, "# whole-line comment")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def _spec(value_dtype="float32", B=64, K=4):
    return BatchSpec(
        batch_size=B, layout="ell", max_nnz=K,
        value_dtype=np.dtype(value_dtype),
    )


def _generic(path, spec, part_index=0, num_parts=1, indexing_mode=0):
    parser = create_parser(
        f"{path}?indexing_mode={indexing_mode}", part_index, num_parts,
        type="libsvm", threaded=False,
    )
    batcher = FixedShapeBatcher(spec)
    out = list(batcher.batches(iter(parser)))
    parser.close()
    return out, batcher.truncated_nnz


def _fused(path, spec, part_index=0, num_parts=1, indexing_mode=0):
    from dmlc_core_tpu.staging import FusedEllLibSVMBatches

    stream = FusedEllLibSVMBatches(
        path, spec, part_index, num_parts, indexing_mode=indexing_mode
    )
    out = [
        type(b)(
            labels=b.labels.copy(), weights=b.weights.copy(),
            n_valid=b.n_valid, indices=b.indices.copy(),
            values=b.values.copy(), nnz=b.nnz.copy(),
        )
        for b in stream
    ]
    tr = stream.truncated_nnz
    stream.close()
    return out, tr


def _assert_equal(fb, gb):
    assert len(fb) == len(gb)
    for f, g in zip(fb, gb):
        assert f.n_valid == g.n_valid
        np.testing.assert_array_equal(f.labels, g.labels)
        np.testing.assert_array_equal(f.weights, g.weights)
        np.testing.assert_array_equal(f.nnz, g.nnz)
        np.testing.assert_array_equal(f.indices, g.indices)
        np.testing.assert_array_equal(f.values, g.values)


@fused
@pytest.mark.parametrize("value_dtype", ["float32", "float16"])
def test_fused_matches_generic(tmp_path, value_dtype):
    path = _write_libsvm(str(tmp_path / "d.svm"), rows=500, k_max=7)
    f, ft = _fused(path, _spec(value_dtype))
    g, gt = _generic(path, _spec(value_dtype))
    _assert_equal(f, g)
    assert ft == gt and ft > 0  # k_max 7 > K=4 → truncation exercised


@fused
def test_fused_matches_generic_junk_qid_comments(tmp_path):
    path = _write_libsvm(
        str(tmp_path / "j.svm"), rows=300, junk=True, qid=True,
        comments=True,
    )
    f, ft = _fused(path, _spec())
    g, gt = _generic(path, _spec())
    _assert_equal(f, g)
    assert ft == gt


@fused
def test_one_based_indexing_modes(tmp_path):
    path = _write_libsvm(str(tmp_path / "o.svm"), rows=200, one_based=True)
    f, _ = _fused(path, _spec(), indexing_mode=1)
    g, _ = _generic(path, _spec(), indexing_mode=1)
    _assert_equal(f, g)
    # auto mode resolves 1-based from the head probe = explicit mode 1
    a, _ = _fused(path, _spec(), indexing_mode=-1)
    _assert_equal(a, f)
    # wrapped ids (0 under 1-based) are zeroed + counted, never negative
    assert all(int(b.indices.min()) >= 0 for b in f)


@fused
def test_sharded_exact_cover(tmp_path):
    path = _write_libsvm(str(tmp_path / "s.svm"), rows=400)
    labels = []
    for part in range(3):
        batches, _ = _fused(path, _spec(B=32), part_index=part, num_parts=3)
        for b in batches:
            labels.extend(b.labels[: b.n_valid].tolist())
    assert len(labels) == 400
    full, _ = _generic(path, _spec(B=400))
    np.testing.assert_array_equal(
        np.sort(np.asarray(labels)), np.sort(full[0].labels[:400])
    )


@fused
def test_dispatcher_routes_libsvm(tmp_path):
    from dmlc_core_tpu.staging import FusedEllLibSVMBatches
    from dmlc_core_tpu.staging.fused import _GenericBatchStream

    path = _write_libsvm(str(tmp_path / "r.svm"), rows=50)
    s = ell_batches(path + "?format=libsvm", _spec())
    assert isinstance(s, FusedEllLibSVMBatches)
    total = sum(int(b.n_valid) for b in s)
    s.close()
    assert total == 50
    # non-fusable spec falls back to the generic path, same totals
    g = ell_batches(
        path + "?format=libsvm",
        BatchSpec(batch_size=64, layout="ell", max_nnz=4,
                  index_dtype=np.dtype(np.int64)),
    )
    assert isinstance(g, _GenericBatchStream)
    assert sum(int(b.n_valid) for b in g) == 50
    g.close()


@fused
def test_threaded_fan_out_covers(tmp_path):
    path = _write_libsvm(str(tmp_path / "t.svm"), rows=300)
    s = ell_batches(path + "?format=libsvm", _spec(B=32), nthread=2)
    labels = [x for b in s for x in b.labels[: b.n_valid].tolist()]
    s.close()
    assert len(labels) == 300


@fused
def test_fuzz_parity(tmp_path):
    """Randomized noisy libsvm text stages identically through the fused
    kernel and the generic path (the libsvm analogue of
    tests/test_libfm_ell.py::test_fuzz_parity; runs under ASan via make
    check)."""
    rng = np.random.default_rng(31)
    junk_pool = ["x", "a:b", "1:2:3", ":", "::", "-:-", "7:", ":9",
                 "1:nan", "qid:zz", "  "]
    for trial in range(12):
        lines = []
        for _ in range(60):
            toks = []
            r = rng.random()
            if r < 0.15:
                toks.append("junklabel")  # line dropped by both paths
            elif r < 0.4:
                toks.append(f"{rng.normal():.4g}:{abs(rng.normal()):.3g}")
            else:
                toks.append(f"{rng.normal():.4g}")
            if rng.random() < 0.3:
                toks.append(f"qid:{int(rng.integers(0, 99))}")
            for _ in range(int(rng.integers(0, 9))):
                if rng.random() < 0.25:
                    toks.append(str(rng.choice(junk_pool)))
                else:
                    feat = int(rng.integers(-2, 3000))
                    if rng.random() < 0.5:
                        toks.append(f"{feat}:{rng.normal():.5g}")
                    else:
                        toks.append(f"{feat}")
            line = " ".join(toks)
            if rng.random() < 0.2:
                line += " # comment 5:5"
            lines.append(line)
        eol = "\r\n" if trial % 3 == 0 else "\n"
        path = str(tmp_path / f"fz{trial}.svm")
        with open(path, "w", newline="") as f:
            f.write(eol.join(lines) + eol)
        for dtype in ("float32", "float16"):
            f_b, f_t = _fused(path, _spec(dtype, B=37, K=4))
            g_b, g_t = _generic(path, _spec(dtype, B=37, K=4))
            _assert_equal(f_b, g_b)
            assert f_t == g_t, (trial, dtype)


def test_generic_fallback_without_native(tmp_path, monkeypatch):
    """ell_batches format=libsvm works (same totals) when the kernel is
    reported missing."""
    path = _write_libsvm(str(tmp_path / "f.svm"), rows=80)
    monkeypatch.setattr(native, "HAS_LIBSVM_ELL", False)
    s = ell_batches(path + "?format=libsvm", _spec())
    assert sum(int(b.n_valid) for b in s) == 80
    s.close()
