"""Tests for Parameter/Registry/Config.

Modeled on reference test/unittest/unittest_param.cc, unittest_config.cc and
example/parameter.cc.
"""

import pytest

from dmlc_core_tpu.params import Config, ParamError, Parameter, Registry, field


class LearnParam(Parameter):
    num_hidden = field(int, default=64, lower=1, help="number of hidden units")
    lr = field(float, default=0.1, lower=0.0, upper=10.0, aliases=("learning_rate",))
    name = field(str, default="net")
    act = field(str, default="relu", enum={"relu": "relu", "sigmoid": "sigmoid"})
    verbose = field(bool, default=False)


def test_param_defaults_and_init():
    p = LearnParam()
    assert p.num_hidden == 64 and p.lr == 0.1 and p.act == "relu"
    p.init({"num_hidden": "128", "lr": "0.5", "verbose": "true"})
    assert p.num_hidden == 128 and p.lr == 0.5 and p.verbose is True


def test_param_range_check():
    p = LearnParam()
    with pytest.raises(ParamError, match="out of range"):
        p.init({"num_hidden": 0})
    with pytest.raises(ParamError, match="out of range"):
        p.init({"lr": 100.0})


def test_param_enum_and_alias():
    p = LearnParam(act="sigmoid", learning_rate=0.9)
    assert p.act == "sigmoid" and p.lr == 0.9
    with pytest.raises(ParamError, match="expected one of"):
        p.init({"act": "softmax"})


def test_param_unknown_key_suggestion():
    p = LearnParam()
    with pytest.raises(ParamError, match="num_hidden"):
        p.init({"num_hiden": 3})  # typo → did-you-mean
    leftover = p.init({"totally_new": 1}, allow_unknown=True)
    assert leftover == {"totally_new": 1}


def test_param_dict_json_doc_roundtrip():
    p = LearnParam(num_hidden=5)
    d = p.to_dict()
    assert d["num_hidden"] == "5"
    q = LearnParam()
    q.load_json(p.save_json())
    assert q == p
    doc = LearnParam.doc()
    assert "num_hidden" in doc and "hidden units" in doc


def test_param_bad_type():
    p = LearnParam()
    with pytest.raises(ParamError, match="Invalid value"):
        p.init({"num_hidden": "not_an_int"})


def test_registry_basic():
    reg = Registry("test_kind")
    try:

        @reg.register("alpha")
        def make_alpha(x):
            return ("alpha", x)

        entry = reg.lookup("alpha").describe("the alpha factory")
        assert entry.description == "the alpha factory"
        assert reg.create("alpha", 3) == ("alpha", 3)
        assert reg.find("missing") is None
        with pytest.raises(Exception, match="already registered"):
            reg.add("alpha", make_alpha)
        reg.add("alpha", lambda x: ("alpha2", x), override=True)
        assert reg.create("alpha", 1) == ("alpha2", 1)
        assert Registry.get("test_kind") is reg
    finally:
        Registry._instances.pop("test_kind", None)


def test_config_parse():
    text = """
    # a comment
    lr = 0.1
    name = "hello world" # trailing
    esc = "a\\"b\\nc"
    n = 3
    """
    cfg = Config(text)
    assert cfg.get("lr") == "0.1"
    assert cfg.get("name") == "hello world"
    assert cfg.get("esc") == 'a"b\nc'
    assert cfg.get("n") == "3"
    assert "lr" in cfg and "missing" not in cfg


def test_config_multi_value_and_order():
    cfg = Config(multi_value=True)
    cfg.load("k = 1\nk = 2\nj = x\n")
    assert cfg.get_all("k") == ["1", "2"]
    assert [kv for kv in cfg] == [("k", "1"), ("k", "2"), ("j", "x")]
    single = Config("k = 1\nk = 2\n")
    assert single.get_all("k") == ["2"]


def test_config_proto_string():
    cfg = Config('a = "x\\ny"\n')
    assert cfg.to_proto_string() == 'a : "x\\ny"\n'


def test_config_errors():
    with pytest.raises(Exception):
        Config("= 1")
    with pytest.raises(Exception):
        Config('k = "unterminated')
    with pytest.raises(Exception):
        Config("k 1")
    with pytest.raises(Exception):
        Config("a = = \nb = c")  # '=' may not be a value
    with pytest.raises(Exception):
        Config("= = x")  # '=' may not be a key


def test_param_optional_none_roundtrip():
    class OptParam(Parameter):
        x = field(int, default=None)
        s = field(str, default=None)

    p = OptParam()
    q = OptParam()
    q.load_json(p.save_json())
    assert q.x is None and q.s is None
    p.init({"x": 3})
    q.load_json(p.save_json())
    assert q.x == 3 and q.s is None


def test_param_required_enforced_with_nonnull_default():
    class ReqParam(Parameter):
        path = field(str, default="", required=True)

    with pytest.raises(ParamError, match="Required parameter"):
        ReqParam().init({})
    p = ReqParam()
    p.init({"path": "x"})
    assert p.path == "x"
