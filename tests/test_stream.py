"""Streaming ingestion (dmlc_core_tpu/stream/, docs/streaming.md):
tail-follow RecordIO sources over a manifest-committed shard directory.

Covers the durable-commit contract on the RecordIO writers (flush never
exposes a partial block), manifest atomicity, writer rotation + EOS,
live-follow vs post-hoc order equivalence (sequential AND windowed
shuffle, including a reader parked mid-window across a rotation), the
chaos fault:// variant, bounded staleness backpressure, `tools info` on
a growing shard, the stream.* telemetry derivations, and THE 2-worker
``dmlc-submit`` drill with the writer rotating mid-job.
"""

import json
import os
import subprocess
import sys
import threading
import time
import zlib  # noqa: L009 (crc32 as an order-free fold, not compression)

import pytest

from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter, RecordIOWriter
from dmlc_core_tpu.io.stream import FileStream
from dmlc_core_tpu.stream import StreamSource, StreamWriter
from dmlc_core_tpu.stream import manifest as sm
from dmlc_core_tpu.utils.logging import Error

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _payload(i: int) -> bytes:
    # variable sizes so codec blocks and windows cut at odd offsets
    return (b"rec-%08d|" % i) * (1 + i % 4)


def _drain(src) -> list:
    out = []
    while True:
        r = src.next_record()
        if r is None:
            return out
        out.append(r)


def _posthoc(d: str, **kw) -> list:
    src = StreamSource(d, **kw)
    try:
        return _drain(src)
    finally:
        src.close()


# -- satellite: the durable-commit contract on the RecordIO writers -----------


def test_commit_returns_frame_aligned_watermark(tmp_path):
    p = str(tmp_path / "w.rec")
    with FileStream(p, "w") as f:
        w = RecordIOWriter(f, codec="zlib", block_bytes=1 << 20)
        for i in range(10):
            w.write_record(_payload(i))
        b, r = w.commit()
    assert r == 10 and b == os.path.getsize(p)
    scan = sm.scan_committed_prefix(p)
    assert scan["committed_bytes"] == b and scan["tail_bytes"] == 0
    # the committed prefix decodes as exactly the appended records
    sp = io_split.create(p, 0, 1, type="recordio", shuffle=None)
    got = _drain(sp)
    sp.close()
    assert got == [_payload(i) for i in range(10)]


def test_flush_never_exposes_partial_block(tmp_path):
    """THE regression: a raw stream flush() mid-codec-block must leave
    only whole frames on disk — the pending block stays in the writer's
    buffer until commit() seals it, so a tail reader can never decode a
    torn block."""
    p = str(tmp_path / "partial.rec")
    f = FileStream(p, "w")
    w = RecordIOWriter(f, codec="zlib", block_bytes=256)
    for i in range(40):  # several sealed blocks + a pending partial one
        w.write_record(_payload(i))
    f.flush()  # what a crashy writer's OS buffers would do
    scan = sm.scan_committed_prefix(p)
    assert scan["tail_bytes"] == 0, "flush exposed a torn frame"
    assert scan["committed_bytes"] == os.path.getsize(p)
    b, r = w.commit()
    f.close()
    assert r == 40
    scan = sm.scan_committed_prefix(p)
    assert scan["committed_bytes"] == b == os.path.getsize(p)
    assert scan["tail_bytes"] == 0


def test_indexed_writer_commit_and_fsync_knob(tmp_path):
    p = str(tmp_path / "idx.rec")
    ip = p + ".idx"
    with FileStream(p, "w") as f, FileStream(ip, "w") as fi:
        w = IndexedRecordIOWriter(f, fi, codec="zlib", block_bytes=512,
                                  fsync=True)
        for i in range(30):
            w.write_record(_payload(i))
        b1, r1 = w.commit()  # fsync=None -> constructor knob (True)
        for i in range(30, 50):
            w.write_record(_payload(i))
        b2, r2 = w.commit(fsync=False)
    assert (r1, r2) == (30, 50) and b2 > b1
    # the sidecar was flushed at commit: both files are durable + whole
    assert os.path.getsize(ip) > 0
    assert sm.scan_committed_prefix(p)["tail_bytes"] == 0


# -- the manifest commit point ------------------------------------------------


def test_manifest_roundtrip_seq_and_missing(tmp_path):
    d = str(tmp_path)
    assert sm.read_manifest(d) is None
    m = sm.new_manifest()
    m["live"] = {"gen": 0, "data": "shard-00000.rec",
                 "index": "shard-00000.rec.idx", "bytes": 0, "records": 0,
                 "committed_unix": 0.0}
    sm.write_manifest(d, m)
    sm.write_manifest(d, m)
    got = sm.read_manifest(d)
    assert got["seq"] == 2 and got["live"]["gen"] == 0
    assert got["sealed"] == [] and got["eos"] is False
    # no torn temp files left behind by the atomic publish
    assert [n for n in os.listdir(d) if n.endswith(".tmp")] == []


def test_manifest_garbage_fails_loudly(tmp_path, monkeypatch):
    monkeypatch.setenv("DMLC_RETRY_BASE_MS", "1")
    (tmp_path / sm.MANIFEST_NAME).write_text("{not json")
    with pytest.raises(Error, match="corrupt stream manifest"):
        sm.read_manifest(str(tmp_path))


# -- writer lifecycle: rotation, EOS, sealed shards ---------------------------


def test_writer_rotates_and_seals_readable_shards(tmp_path):
    d = str(tmp_path)
    with StreamWriter(d, codec="zlib", block_bytes=512, rotate_bytes=2048,
                      commit_records=25) as w:
        for i in range(300):
            w.append(_payload(i))
    m = sm.read_manifest(d)
    assert m["eos"] is True and m["live"] is None
    assert len(m["sealed"]) >= 2, "rotate_bytes=2048 never rotated"
    assert sum(e["records"] for e in m["sealed"]) == 300
    nxt = 0
    for ent in m["sealed"]:
        shard = os.path.join(d, ent["data"])
        scan = sm.scan_committed_prefix(shard)
        assert scan["tail_bytes"] == 0
        assert scan["committed_bytes"] == ent["bytes"] == os.path.getsize(
            shard
        )
        # each sealed shard reads as a plain indexed recordio dataset
        sp = io_split.create(shard, 0, 1, type="recordio",
                             index_uri=os.path.join(d, ent["index"]))
        got = _drain(sp)
        sp.close()
        assert got == [_payload(i) for i in range(nxt, nxt + ent["records"])]
        nxt += ent["records"]
    assert nxt == 300


def test_writer_empty_rotation_and_empty_close(tmp_path):
    d = str(tmp_path)
    w = StreamWriter(d, codec=None)
    w.rotate()  # nothing appended: must not seal an empty generation
    w.close(eos=True)
    m = sm.read_manifest(d)
    assert m["sealed"] == [] and m["live"] is None and m["eos"] is True
    # the empty live shard's files were dropped, not sealed
    assert [n for n in os.listdir(d) if n.endswith(".rec")] == []


# -- live follow == post-hoc read ---------------------------------------------


def test_live_follow_sequential_matches_posthoc(tmp_path):
    d = str(tmp_path)
    expect = [_payload(i) for i in range(400)]

    def produce():
        with StreamWriter(d, codec="zlib", block_bytes=512,
                          rotate_bytes=4096, commit_records=20) as w:
            for i, rec in enumerate(expect):
                w.append(rec)
                if i % 50 == 49:
                    time.sleep(0.01)  # let the follower catch the tail

    t = threading.Thread(target=produce)
    t.start()
    src = StreamSource(d, poll_secs=0.005, max_idle_secs=30.0)
    live = _drain(src)
    stats = src.io_stats()
    src.close()
    t.join()
    assert live == expect
    assert _posthoc(d) == expect
    assert stats["commits_seen"] >= 2 and stats["rotations_seen"] >= 1


def test_live_follow_shuffled_rotation_race_matches_posthoc(tmp_path):
    """The rotation-race acceptance: a reader parked MID-WINDOW when
    the writer seals the live shard must flush the partial window at
    the boundary and produce exactly the order a post-hoc read of the
    sealed directory produces (same seed -> same window permutations)."""
    d = str(tmp_path)
    kw = dict(shuffle="window", seed=11, window=64)
    w = StreamWriter(d, codec="zlib", block_bytes=512,
                     rotate_bytes=1 << 30, commit_records=0)
    src = StreamSource(d, poll_secs=0.005, max_idle_secs=30.0, **kw)
    for i in range(100):
        w.append(_payload(i))
    w.commit()
    # one full window is ready; the 36 leftovers are pending mid-window
    live = [src.next_record() for _ in range(64)]
    w.rotate()  # seal gen 0 under the reader's feet
    for i in range(100, 150):
        w.append(_payload(i))
    w.close(eos=True)
    live += _drain(src)
    src.close()
    assert sorted(live) == sorted(_payload(i) for i in range(150))
    assert live == _posthoc(d, **kw)
    # per-shard order is bit-identical: shard boundaries partition the
    # sequence at the sealed record counts
    m = sm.read_manifest(d)
    assert [e["records"] for e in m["sealed"]] == [100, 50]
    assert sorted(live[:100]) == sorted(_payload(i) for i in range(100))


def test_live_follow_chaos_faults_heal(tmp_path):
    """The fault:// variant: transient open errors + mid-read resets on
    BOTH the manifest and the shard tails heal through the retry layer
    (retries > 0) without reordering or dropping a record."""
    from dmlc_core_tpu.io import retry

    d = str(tmp_path)
    expect = [_payload(i) for i in range(200)]
    with StreamWriter(d, codec="zlib", block_bytes=512, rotate_bytes=4096,
                      commit_records=40) as w:
        for rec in expect:
            w.append(rec)
    before = retry.stats()
    got = _posthoc(f"fault://errors=2,resets=1,seed=7{d}", poll_secs=0.005,
                   max_idle_secs=30.0)
    delta = retry.stats_delta(before)
    assert got == expect
    assert delta["retries"] > 0, "the chaos run never exercised a retry"


# -- bounded staleness (DMLC_STREAM_MAX_LAG) ----------------------------------


def test_writer_blocks_on_reader_lag_then_resumes(tmp_path):
    d = str(tmp_path)
    w = StreamWriter(d, codec=None, commit_records=10, max_lag=30,
                     lag_policy="block", lag_poll_secs=0.005)
    src = StreamSource(d, poll_secs=0.005, ack_id="r0", max_idle_secs=30.0)
    done = threading.Event()

    def produce():
        for i in range(120):
            w.append(_payload(i))
        done.set()

    # an ack at 0 records makes the writer's lag observable immediately
    sm.write_ack(d, "r0", 0)
    t = threading.Thread(target=produce)
    t.start()
    time.sleep(0.3)
    assert not done.is_set(), "writer never blocked at max_lag=30"
    assert w.backpressure_waits >= 1
    assert w.records_appended < 120
    got = []
    while len(got) < 120:  # drain; acks ride _account and release the writer
        r = src.next_record()
        assert r is not None
        got.append(r)
    t.join(timeout=30)
    assert done.is_set()
    w.close(eos=True)
    src.close()
    assert got == [_payload(i) for i in range(120)]
    assert w.stats()["backpressure_secs"] > 0


def test_writer_lag_policy_warn_never_blocks(tmp_path):
    d = str(tmp_path)
    sm.write_ack(d, "r0", 0)
    with StreamWriter(d, codec=None, max_lag=5, lag_policy="warn") as w:
        for i in range(50):
            w.append(_payload(i))
        assert w.backpressure_waits == 0


# -- tools info on a growing shard --------------------------------------------


def test_tools_info_growing_shard_reports_uncommitted_tail(tmp_path, capsys):
    from dmlc_core_tpu.tools import main as tools_main

    d = str(tmp_path)
    w = StreamWriter(d, codec="zlib", block_bytes=512, commit_records=0)
    for i in range(60):
        w.append(_payload(i))
    w.commit()
    live = sm.read_manifest(d)["live"]
    shard = os.path.join(d, live["data"])
    # a mid-write data tail: half a frame header past the watermark
    with open(shard, "ab") as f:
        f.write(b"\x0a\x23\xd7\xce\x40")
    assert tools_main(["info", shard]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["shard"]["status"] == "growing (tail_bytes=5 uncommitted)"
    assert report["shard"]["committed_bytes"] == live["bytes"]
    assert report["shard"]["blocks"] > 0
    w.close(eos=False)


# -- telemetry: derive, merge, and the top lag column -------------------------


def test_timeseries_derives_stream_lag(tmp_path):
    from dmlc_core_tpu.telemetry import timeseries as ts

    def sample(t, seq, lag_r, lag_s, wm):
        return {"t": t, "seq": seq, "counters": {}, "histograms": {},
                "gauges": {"stream.lag_records": lag_r,
                           "stream.lag_seconds": lag_s,
                           "stream.watermark_records": wm}}

    win = ts.windowed([sample(100.0, 1, 40.0, 0.5, 200.0),
                       sample(110.0, 2, 10.0, 1.25, 400.0)], 60.0)
    assert win["derived"]["stream_lag_records"] == 10.0
    assert win["derived"]["stream_lag_seconds"] == 1.25
    assert win["derived"]["stream_watermark_records"] == 400.0
    # cluster staleness is the SLOWEST follower's, never an average
    views = {
        str(i): {"samples": 2, "counters": {}, "gauges": {},
                 "derived": {"rows_per_sec": 1.0, "stream_lag_seconds": s,
                             "stream_lag_records": r,
                             "stream_watermark_records": 400.0}}
        for i, (s, r) in enumerate(((0.2, 5.0), (3.5, 90.0)))
    }
    merged = ts.merge_windows(views)
    assert merged["derived"]["stream_lag_seconds"] == 3.5
    assert merged["derived"]["stream_lag_records"] == 90.0


def test_top_model_and_render_show_lag_column():
    from dmlc_core_tpu.tools import _render_top, _top_model

    def rank(lag_s, lag_r):
        return {"samples": 3, "counters": {}, "gauges": {},
                "derived": {"rows_per_sec": 10.0, "stall_fraction": {},
                            "stream_lag_seconds": lag_s,
                            "stream_lag_records": lag_r,
                            "stream_watermark_records": 500.0}}

    report = {
        "windowed": {
            "per_rank": {"0": rank(0.25, 3.0), "1": rank(2.5, 80.0)},
            "cluster": {"n_ranks": 2,
                        "derived": {"rows_per_sec": 20.0,
                                    "stall_fraction": {},
                                    "stream_lag_seconds": 2.5,
                                    "stream_lag_records": 80.0}},
        }
    }
    model = _top_model(report, 30.0)
    assert model["ranks"]["1"]["stream_lag_seconds"] == 2.5
    txt = _render_top(model, "127.0.0.1:9999")
    assert "lag" in txt, txt
    assert "0.25s" in txt and "2.50s" in txt
    assert "stream lag 2.50s/80 recs" in txt
    # a sealed-corpus job (no stream keys) renders without the column
    for r in report["windowed"]["per_rank"].values():
        for k in list(r["derived"]):
            if k.startswith("stream_"):
                del r["derived"][k]
    report["windowed"]["cluster"]["derived"] = {
        "rows_per_sec": 20.0, "stall_fraction": {}}
    plain = _render_top(_top_model(report, 30.0), "127.0.0.1:9999")
    assert "lag" not in plain


def test_stream_tail_wait_is_a_stall_stage():
    from dmlc_core_tpu.telemetry.tracing import _WAIT_STAGES

    assert "stream_tail_wait" in _WAIT_STAGES


# -- the fused staging-path gather contract -----------------------------------


def test_create_routes_manifest_uri_and_gathers(tmp_path):
    d = str(tmp_path)
    with StreamWriter(d, codec="zlib", block_bytes=512, rotate_bytes=4096,
                      commit_records=50) as w:
        for i in range(300):
            w.append(_payload(i))
    sp = io_split.create(d + "/manifest.json?shuffle=window&window=32&seed=3",
                         0, 1)
    assert isinstance(sp, StreamSource) and sp.supports_gather()
    seen = []
    while True:
        g = sp.next_gather_batch(48)
        if g is None:
            break
        buf, starts, sizes = g
        assert len(starts) == len(sizes) and len(starts) <= 48
        for s, z in zip(starts.tolist(), sizes.tolist()):
            for rec in sp.extract_records(bytes(buf[s:s + z])):
                seen.append(rec)
    sp.close()
    assert sorted(seen) == sorted(_payload(i) for i in range(300))
    # dataset-level equivalence with the record-shaped drain
    assert seen == _posthoc(d, shuffle="window", seed=3, window=32)


def test_stream_source_is_single_reader_unless_dynamic(tmp_path):
    d = str(tmp_path)
    with StreamWriter(d, codec=None) as w:
        w.append(b"x")
    with pytest.raises(Error, match="dynamic_shards"):
        io_split.create(d + "/manifest.json", 1, 2)
    with pytest.raises(Error, match="cachefile"):
        io_split.create(d + "/manifest.json#cache.rec", 0, 1)


# -- THE dmlc-submit acceptance: writer rotating mid-job ----------------------

_STREAM_WORKER = """
import json, os, sys, time, zlib
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.tracker.client import RabitWorker
w = RabitWorker()
rank = w.start()
sp = io_split.create(
    {d!r} + "/manifest.json?dynamic_shards=1&shuffle=window"
           + "&window=64&seed=9",
    threaded=False)
events = []
sp.on_shard_done = lambda gen, shard, status: events.append(
    [gen, shard, status])
by_gen = {{}}
theta = 0
rows = 0
while True:
    rec = sp.next_record()
    if rec is None:
        break
    by_gen.setdefault(str(sp.generation), []).append(zlib.crc32(rec))
    theta += zlib.crc32(rec)  # order-independent integer "gradient"
    rows += 1
    time.sleep(0.002)  # pace the drain across a few sample intervals
sp.close()
with open(os.path.join({out!r}, "worker-%d.json" % rank), "w") as f:
    json.dump({{"rank": rank, "rows": rows, "theta": theta,
               "by_gen": by_gen, "events": events}}, f)
w.heartbeat()  # ships the ring's samples (stream.* gauges included)
w.shutdown()
"""

N_DRILL = 600


def test_submit_run_streaming_rotation_exactly_once(tmp_path):
    """ISSUE 19 acceptance: a 2-worker ``dmlc-submit`` job follows a
    stream whose writer rotates MID-JOB. The trained (order-independent
    integer) model state and the per-shard content hashes must be
    bit-identical to a post-hoc read of the sealed shards, every
    micro-shard must commit exactly once, and the end-of-job report
    must carry the stream lag column ``tools top`` renders."""
    from dmlc_core_tpu.telemetry import timeseries as ts
    from dmlc_core_tpu.tools import _render_top, _top_model

    d = str(tmp_path / "stream")
    os.makedirs(d)
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir)
    report_path = tmp_path / "metrics_report.json"
    script = tmp_path / "worker.py"
    script.write_text(_STREAM_WORKER.format(repo=REPO, d=d, out=out_dir))

    def produce():
        with StreamWriter(d, codec=None, rotate_bytes=4096,
                          commit_records=40) as w:
            for i in range(N_DRILL):
                w.append(_payload(i))
                time.sleep(0.004)  # rotations land while workers drain

    t = threading.Thread(target=produce)
    t.start()
    try:
        run = subprocess.run(
            [sys.executable, "-m", "dmlc_core_tpu.tracker.submit",
             "--cluster", "local", "--num-workers", "2",
             "--host-ip", "127.0.0.1",
             sys.executable, str(script)],
            capture_output=True, text=True, timeout=150,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "DMLC_TS_INTERVAL": "0.1",
                 "DMLC_METRICS_REPORT": str(report_path)},
            cwd=REPO,
        )
    finally:
        t.join()
    assert run.returncode == 0, run.stderr[-3000:]

    outs = [json.load(open(os.path.join(out_dir, f)))
            for f in sorted(os.listdir(out_dir))]
    assert len(outs) == 2 and {o["rank"] for o in outs} == {0, 1}

    # the sealed truth: every record landed in exactly one sealed shard
    m = sm.read_manifest(d)
    assert m["eos"] is True and len(m["sealed"]) >= 3, (
        "the writer never rotated mid-job")
    sealed_by_gen = {}
    nxt = 0
    for ent in m["sealed"]:
        recs = [_payload(i) for i in range(nxt, nxt + ent["records"])]
        sealed_by_gen[str(ent["gen"])] = sorted(
            zlib.crc32(r) for r in recs)
        nxt += ent["records"]
    assert nxt == N_DRILL

    # exactly-once at record level: the union of both workers' records
    # is the corpus, no duplicates, none lost
    assert sum(o["rows"] for o in outs) == N_DRILL
    consumed = sorted(c for o in outs for v in o["by_gen"].values()
                      for c in v)
    assert consumed == sorted(c for v in sealed_by_gen.values() for c in v)

    # per-shard content hashes bit-identical to the sealed reads
    for gen, want in sealed_by_gen.items():
        got = sorted(c for o in outs for c in o["by_gen"].get(gen, []))
        assert got == want, f"generation {gen} content drifted"

    # trained model state bit-identical (order-independent integers)
    assert sum(o["theta"] for o in outs) == sum(
        c for v in sealed_by_gen.values() for c in v)

    # every micro-shard committed exactly once cluster-wide
    recorded = [tuple(e[:2]) for o in outs for e in o["events"]
                if e[2] == "recorded"]
    assert len(recorded) == len(set(recorded)), "a micro-shard double-committed"
    assert len(recorded) > 0
    gens_done = {g for g, _ in recorded}
    assert gens_done == set(int(g) for g in sealed_by_gen), (
        "some generation finished without a recorded micro-shard")

    # the report carries the stream staleness family and tools top
    # renders the live lag column from it
    report = json.loads(report_path.read_text())
    per_rank = report["timeseries"]["per_rank"]
    assert {"0", "1"} <= set(per_rank)
    views = {r: ts.windowed(per_rank[r], 120.0) for r in per_rank}
    lagged = [r for r in ("0", "1")
              if "stream_lag_seconds" in views[r]["derived"]]
    assert lagged, "no rank shipped stream.* gauges"
    model = _top_model(
        {"windowed": {"per_rank": views,
                      "cluster": ts.merge_windows(views)}}, 120.0)
    txt = _render_top(model, "127.0.0.1:9999")
    assert "stream lag" in txt and "lag" in txt
