"""Disaggregated preprocessing service (dmlc_core_tpu/dsserve/,
docs/dsserve.md): wire-frame round trips and hostility, the
``dsserve://`` staging producer's bit-identity with the all-local
pipeline across v1/zlib containers × fused/generic batchers, static
reopen-and-seek resume, StagingPipeline composition (packed single-DMA
path engaged on received slots), and the chaos drill — one of two real
server processes SIGKILLed mid-stream, the client failing over through
the shard ledger with exactly-once accounting and clean-run-identical
rows."""

import hashlib
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dmlc_core_tpu.data.rowrec import encode_row
from dmlc_core_tpu.dsserve import (
    DsServeBatches,
    DsServeServer,
    parse_dsserve_uri,
)
from dmlc_core_tpu.dsserve import wire
from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
from dmlc_core_tpu.io.stream import FileStream
from dmlc_core_tpu.staging import fused
from dmlc_core_tpu.staging.batcher import BatchSpec
from dmlc_core_tpu.tracker.tracker import RabitTracker
from dmlc_core_tpu.utils.logging import Error

N_ROWS = 2000
K = 8
BATCH = 64


def _write_corpus(rec, idx, codec=None):
    kwargs = {"codec": codec, "block_bytes": 1 << 14} if codec else {}
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(f, fi, **kwargs)
        rng = np.random.default_rng(7)
        for i in range(N_ROWS):
            idxs = rng.integers(0, 500, K, dtype=np.int64)
            vals = rng.normal(size=K).astype(np.float32)
            w.write_record(encode_row(float(i % 2), idxs, vals), i)
        w.flush_block()
    return rec, idx


@pytest.fixture
def corpus(tmp_path):
    return _write_corpus(str(tmp_path / "d.rec"), str(tmp_path / "d.idx"))


@pytest.fixture
def corpus_zlib(tmp_path):
    return _write_corpus(
        str(tmp_path / "z.rec"), str(tmp_path / "z.idx"), codec="zlib"
    )


@pytest.fixture
def tracker(monkeypatch):
    monkeypatch.setenv("DMLC_SHARD_OVERSPLIT", "6")
    # the ShardService reads the TTL at construction — it must be
    # pinned BEFORE the tracker exists for the chaos drill's stranded
    # lease to be reclaimed in seconds, not the 30s default
    monkeypatch.setenv("DMLC_SHARD_LEASE_TTL", "2.0")
    t = RabitTracker("127.0.0.1", 1)
    t.start(1)
    monkeypatch.setenv("DMLC_TRACKER_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_TRACKER_PORT", str(t.port))
    monkeypatch.setenv("DMLC_TASK_ID", "0")
    monkeypatch.delenv("DMLC_SHARD_RANK", raising=False)
    yield t
    t.close()


def _spec(overflow="truncate"):
    return BatchSpec(batch_size=BATCH, layout="ell", max_nnz=K,
                     overflow=overflow)


def _uri(rec, idx, extra=""):
    return f"{rec}?index={idx}&shuffle=record&seed=3{extra}"


def _drain_packed(producer):
    """(rows, sha256 over every packed slot's bytes, slot count)."""
    h = hashlib.sha256()
    rows = slots = 0
    for b in producer:
        h.update(b.packed.tobytes())
        rows += b.n_valid
        slots += 1
    return rows, h.hexdigest(), slots


# -- wire unit ----------------------------------------------------------------


class _Pipe:
    """Loopback socket pair for frame round-trip tests."""

    def __enter__(self):
        self.a, self.b = socket.socketpair()
        return self.a, self.b

    def __exit__(self, *exc):
        self.a.close()
        self.b.close()


def test_wire_frame_roundtrip():
    payload = np.arange(256, dtype=np.uint8)
    with _Pipe() as (a, b):
        wire.send_frame(
            a, wire.KIND_SLOT, {"shard": 3}, payload, seq=7, epoch=2
        )
        kind, meta, got, seq, epoch = wire.recv_frame(b)
    assert kind == wire.KIND_SLOT
    assert meta == {"shard": 3}
    assert seq == 7 and epoch == 2
    assert np.array_equal(got, payload)


def test_wire_meta_only_frame():
    with _Pipe() as (a, b):
        wire.send_frame(a, wire.KIND_EPOCH_END, {"slots": 9})
        kind, meta, payload, _seq, _epoch = wire.recv_frame(b)
    assert kind == wire.KIND_EPOCH_END
    assert meta == {"slots": 9} and payload is None


def test_wire_crc_mismatch_raises():
    payload = np.arange(64, dtype=np.uint8)
    with _Pipe() as (a, b):
        wire.send_frame(a, wire.KIND_SLOT, {"shard": 0}, payload)
        raw = b.recv(4096)
        # flip one payload byte past the header+meta
        corrupted = bytearray(raw)
        corrupted[-1] ^= 0xFF
        a2, b2 = socket.socketpair()
        try:
            a2.sendall(bytes(corrupted))
            with pytest.raises(Error, match="crc mismatch"):
                wire.recv_frame(b2)
        finally:
            a2.close()
            b2.close()


def test_wire_bad_magic_and_hostile_lengths():
    with _Pipe() as (a, b):
        a.sendall(b"\x00" * wire.HDR_BYTES)
        with pytest.raises(Error, match="magic"):
            wire.recv_frame(b)
    # hostile meta length: a valid magic with an absurd meta_len
    import struct  # test-side frame crafting (L015 scopes library code)

    hdr = struct.pack(
        "<IBBHqiIII", wire.MAGIC, wire.KIND_SLOT, 0, 0, 0, 0,
        wire.MAX_META + 1, 0, 0,
    )
    with _Pipe() as (a, b):
        a.sendall(hdr)
        with pytest.raises(Error, match="hostile"):
            wire.recv_frame(b)


def test_wire_truncated_frame_raises():
    payload = np.arange(64, dtype=np.uint8)
    with _Pipe() as (a, b):
        wire.send_frame(a, wire.KIND_SLOT, {"shard": 0}, payload)
        raw = b.recv(4096)
        a2, b2 = socket.socketpair()
        try:
            a2.sendall(raw[:-10])
            a2.close()  # EOF mid-payload
            with pytest.raises((Error, ConnectionError)):
                wire.recv_frame(b2)
        finally:
            b2.close()


def test_parse_dsserve_uri():
    eps, inner = parse_dsserve_uri(
        "dsserve://h1:70,h2:71/data/x.rec?index=/data/x.idx"
    )
    assert eps == [("h1", 70), ("h2", 71)]
    assert inner == "/data/x.rec?index=/data/x.idx"
    # nested scheme passes through
    _eps, inner = parse_dsserve_uri("dsserve://h:1/s3://b/k.rec")
    assert inner == "s3://b/k.rec"
    with pytest.raises(Error):
        parse_dsserve_uri("dsserve://hostonly/x.rec")
    with pytest.raises(Error):
        parse_dsserve_uri("dsserve://h:1")


# -- bit-identity: dsserve == all-local ---------------------------------------


def test_static_single_server_bit_identical_to_local(corpus):
    """One server, no tracker: the remote stream IS the local pipeline
    — every packed slot bit-identical, headline determinism contract."""
    rec, idx = corpus
    spec = _spec()
    local = fused.ell_batches(_uri(rec, idx), spec)
    rows_l, sha_l, slots_l = _drain_packed(local)
    local.close()
    srv = DsServeServer().start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", spec,
            mode="static",
        )
        rows_r, sha_r, slots_r = _drain_packed(c)
        c.close()
    finally:
        srv.close()
    assert (rows_r, sha_r, slots_r) == (rows_l, sha_l, slots_l)
    assert rows_r == N_ROWS


def test_factory_routes_dsserve_uri(corpus):
    rec, idx = corpus
    srv = DsServeServer().start()
    try:
        src = fused.ell_batches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", _spec()
        )
        assert isinstance(src, DsServeBatches)
        rows, _sha, _slots = _drain_packed(src)
        src.close()
        assert rows == N_ROWS
        # static args are meaningless for a remote stripe — loud error
        with pytest.raises(Error, match="stripe"):
            fused.ell_batches(
                f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}",
                _spec(), part_index=1, num_parts=2,
            )
    finally:
        srv.close()


@pytest.mark.parametrize("container", ["v1", "zlib"])
@pytest.mark.parametrize("path", ["fused", "generic"])
def test_leased_bit_identity_matrix(
    container, path, corpus, corpus_zlib, tracker
):
    """The acceptance matrix: tracker-leased dsserve drain (2 in-process
    servers) produces per-micro-shard packed bytes BIT-IDENTICAL to
    static per-shard local drains, across v1/zlib containers and
    fused/generic batcher paths (overflow='error' forces the generic
    FixedShapeBatcher — same slot layout, no native kernel)."""
    rec, idx = corpus if container == "v1" else corpus_zlib
    spec = _spec(overflow="error" if path == "generic" else "truncate")
    uri = _uri(rec, idx)
    s1 = DsServeServer(rank=101).start()
    s2 = DsServeServer(rank=102).start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{s1.port},127.0.0.1:{s2.port}{uri}",
            spec, mode="lease",
        )
        shas = {}
        rows = 0

        def on_slot(shard, seq, payload):
            shas.setdefault(shard, hashlib.sha256()).update(
                payload.tobytes()
            )

        c.on_slot = on_slot
        for b in c:
            rows += b.n_valid
        stats = c.io_stats()
        c.close()
    finally:
        s1.close()
        s2.close()
    summary = tracker.shards.summary()
    M = summary["n_shards"]
    assert rows == N_ROWS
    assert summary["completed"] == M
    assert stats["shards_recorded"] == M
    assert sorted(shas) == list(range(M))
    for i in range(M):
        p = fused.ell_batches(uri, spec, part_index=i, num_parts=M)
        _rows, sha, _slots = _drain_packed(p)
        p.close()
        assert shas[i].hexdigest() == sha, f"micro-shard {i} bytes differ"


def test_empty_micro_shards_commit_and_epoch_completes(tmp_path, tracker):
    """An oversplit beyond the corpus row count makes some micro-shards
    ZERO-row; their SHARD_FIN arrives with no slots and must still be
    committed (regression: gating commit on received slots left empty
    shards unaccounted — the ledger never completed and the drain hung
    forever)."""
    rec = str(tmp_path / "tiny.rec")
    idx = str(tmp_path / "tiny.idx")
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(f, fi)
        rng = np.random.default_rng(3)
        for i in range(4):  # 4 rows < 6 micro-shards → >= 2 empty shards
            w.write_record(encode_row(
                float(i), rng.integers(0, 9, K, dtype=np.int64),
                rng.normal(size=K).astype(np.float32),
            ), i)
        w.flush_block()
    srv = DsServeServer(rank=101).start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", _spec(),
            mode="lease",
        )
        done = []
        c.on_shard_done = lambda shard, status: done.append((shard, status))
        rows = sum(b.n_valid for b in c)
        c.close()
    finally:
        srv.close()
    summary = tracker.shards.summary()
    M = summary["n_shards"]
    assert rows == 4
    assert summary["completed"] == M  # empty shards accounted too
    assert sorted(s for s, _ in done) == list(range(M))


def test_epoch_rides_the_stream(corpus, tracker):
    """epoch=1 through dsserve == epoch 1's deterministic permutation
    locally (the (seed, epoch) contract crosses the wire)."""
    rec, idx = corpus
    spec = _spec()
    srv = DsServeServer(rank=101).start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", spec,
            mode="lease", epoch=1,
        )
        shas = {}
        c.on_slot = lambda shard, seq, p: shas.setdefault(
            shard, hashlib.sha256()
        ).update(p.tobytes())
        rows = sum(b.n_valid for b in c)
        c.close()
    finally:
        srv.close()
    M = tracker.shards.summary()["n_shards"]
    assert rows == N_ROWS
    for i in range(M):
        p = fused.ell_batches(
            _uri(rec, idx, "&epoch=1"), spec, part_index=i, num_parts=M
        )
        _rows, sha, _slots = _drain_packed(p)
        p.close()
        assert shas[i].hexdigest() == sha
    # and it is NOT epoch 0's order (the permutation actually moved)
    p0 = fused.ell_batches(_uri(rec, idx), spec, part_index=0, num_parts=M)
    _r, sha0, _s = _drain_packed(p0)
    p0.close()
    assert sha0 != shas[0].hexdigest()


# -- static resume (reopen-and-seek) ------------------------------------------


def test_static_resume_skips_delivered_slots(corpus):
    """HELLO.start_seq is the RetryingReadStream-style seek: the
    deterministic stream re-runs and the first k slots are skipped —
    the resumed tail is bit-identical to the full stream's tail."""
    rec, idx = corpus
    spec = _spec()
    srv = DsServeServer().start()
    try:
        full = []
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", spec,
            mode="static",
        )
        for b in c:
            full.append(b.packed.tobytes())
        c.close()
        # hand-rolled resumed stream from slot 5
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            hello = {
                "uri": _uri(rec, idx), "format": "auto", "epoch": 0,
                "mode": "static", "part": 0, "nparts": 1, "start_seq": 5,
                "spec": {"batch_size": BATCH, "layout": "ell",
                         "max_nnz": K, "num_features": None,
                         "overflow": "truncate", "index_dtype": "int32",
                         "value_dtype": "float32"},
            }
            wire.send_frame(sock, wire.KIND_HELLO, hello)
            kind, _m, _p, _s, _e = wire.recv_frame(sock)
            assert kind == wire.KIND_OK
            tail = []
            while True:
                kind, meta, payload, seq, _e = wire.recv_frame(sock)
                if kind == wire.KIND_EPOCH_END:
                    break
                if kind == wire.KIND_SLOT:
                    assert seq >= 5
                    tail.append(payload.tobytes())
        finally:
            sock.close()
    finally:
        srv.close()
    assert tail == full[5:]


# -- StagingPipeline composition ----------------------------------------------


def test_staging_pipeline_over_dsserve(corpus):
    """The received slots ride the packed single-DMA staging path
    exactly like local producer batches: same staged values, packed
    path engaged."""
    jax = pytest.importorskip("jax")
    from dmlc_core_tpu.staging.pipeline import StagingPipeline, drain_close

    rec, idx = corpus
    spec = _spec()
    local = fused.ell_batches(_uri(rec, idx), spec)
    want = []
    for b in local:
        want.append((b.n_valid, np.asarray(b.indices).copy(),
                     np.asarray(b.values).copy()))
    local.close()
    srv = DsServeServer().start()
    try:
        src = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", spec,
            mode="static",
        )
        pipe = StagingPipeline(src, device=jax.local_devices()[0])
        got = [
            (np.asarray(d["indices"]), np.asarray(d["values"]))
            for d in pipe
        ]
        stats = pipe.staging_stats()
        drain_close(pipe, src)
    finally:
        srv.close()
    assert len(got) == len(want)
    for (nv, wi, wv), (gi, gv) in zip(want, got):
        np.testing.assert_array_equal(wi, gi)
        np.testing.assert_array_equal(wv, gv)
    assert stats["packed_batches"] == len(want)  # single-DMA path engaged
    assert stats["per_array_batches"] == 0


# -- chaos drill --------------------------------------------------------------


def _spawn_server(tmp_path, i, env_extra):
    pf = str(tmp_path / f"srv{i}.port")
    env = os.environ.copy()
    env.update({"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
    env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, "-m", "dmlc_core_tpu.tools", "dsserve", "serve",
         "--port", "0", "--port-file", pf, "--rank", str(100 + i)],
        env=env,
    )
    deadline = time.monotonic() + 20
    while not os.path.exists(pf):
        assert proc.poll() is None, f"server {i} died at startup"
        assert time.monotonic() < deadline, f"server {i} never bound"
        time.sleep(0.05)
    with open(pf) as f:
        ep = json.load(f)
    return proc, f"{ep['host']}:{ep['port']}"


def test_chaos_server_sigkill_mid_stream_fails_over(
    corpus, tracker, tmp_path, monkeypatch
):
    """THE acceptance drill: two REAL server processes, one dies
    (os._exit via the seeded kill-after-slots chaos knob — always
    mid-shard) → its connection drops, its lease is TTL-reclaimed, the
    survivor re-serves the stranded micro-shard in full, and the drain
    completes with exactly-once ledger accounting and per-shard bytes
    identical to a clean local run. No duplicated, no lost rows."""
    rec, idx = corpus
    uri = _uri(rec, idx)  # TTL pinned to 2s by the tracker fixture
    base_env = {
        "DMLC_TRACKER_URI": "127.0.0.1",
        "DMLC_TRACKER_PORT": str(tracker.port),
    }
    victim, ep0 = _spawn_server(
        tmp_path, 0,
        {**base_env, "DMLC_DSSERVE_KILL_AFTER_SLOTS": "3"},
    )
    survivor, ep1 = _spawn_server(tmp_path, 1, base_env)
    try:
        c = DsServeBatches(
            f"dsserve://{ep0},{ep1}{uri}", _spec(), mode="lease",
        )
        shas = {}
        c.on_slot = lambda shard, seq, p: shas.setdefault(
            shard, hashlib.sha256()
        ).update(p.tobytes())
        rows = sum(b.n_valid for b in c)
        stats = c.io_stats()
        c.close()
        assert victim.wait(timeout=30) == 9  # the chaos knob fired
    finally:
        for p in (victim, survivor):
            if p.poll() is None:
                p.kill()
                p.wait()
    summary = tracker.shards.summary()
    M = summary["n_shards"]
    assert rows == N_ROWS
    assert summary["completed"] == M  # exactly-once, cluster-wide
    assert summary["reclaimed"] >= 1  # the victim died holding a lease
    assert stats["endpoints_dead"] == 1
    assert stats["shards_recorded"] == M
    # clean local reference, shard for shard — failover re-served the
    # stranded shard in FULL (the victim's partial stream was dropped
    # with its connection, so nothing duplicated and nothing lost)
    for i in range(M):
        p = fused.ell_batches(uri, _spec(), part_index=i, num_parts=M)
        _rows, sha, _slots = _drain_packed(p)
        p.close()
        assert shas[i].hexdigest() == sha, f"micro-shard {i} bytes differ"


def test_finned_uncommitted_lease_released_on_client_death(corpus, tracker):
    """A client that dies AFTER receiving a shard's SHARD_FIN but
    BEFORE committing it must not strand the lease: the commit belongs
    to the client, so the server releases every lease the dead stream
    ever took — including FIN'd ones (regression: only un-FIN'd leases
    were released, and rank-wide renews from a sibling stream of the
    same server could keep the orphan alive past any TTL). A fresh
    client must then complete the epoch."""
    from dmlc_core_tpu.io.split import fileset_signature

    rec, idx = corpus
    srv = DsServeServer(rank=101).start()
    try:
        # the type resolves exactly as create() resolves it: an
        # indexed dataset signs as indexed_recordio
        sig = fileset_signature(rec, idx, "indexed_recordio")
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            wire.send_frame(sock, wire.KIND_HELLO, {
                "uri": _uri(rec, idx), "mode": "lease", "epoch": 0,
                "fileset": sig,
                "spec": {"batch_size": BATCH, "layout": "ell",
                         "max_nnz": K, "num_features": None,
                         "overflow": "truncate", "index_dtype": "int32",
                         "value_dtype": "float32"},
            })
            kind, _m, _p, _s, _e = wire.recv_frame(sock)
            assert kind == wire.KIND_OK
            while True:  # read up to the FIRST shard's FIN, then die
                kind, _m, _p, _s, _e = wire.recv_frame(sock)
                if kind == wire.KIND_SHARD_FIN:
                    break
        finally:
            sock.close()  # dead client: the FIN'd shard never commits
        # the server notices on its next send and releases EVERY lease
        # its stream took (the FIN'd one included) back to the queue
        deadline = time.monotonic() + 10
        while tracker.shards.summary()["reclaimed"] < 1:
            assert time.monotonic() < deadline, "lease never released"
            time.sleep(0.05)
        # a fresh client completes the epoch — nothing stays stranded
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", _spec(),
            mode="lease",
        )
        rows = sum(b.n_valid for b in c)
        c.close()
    finally:
        srv.close()
    summary = tracker.shards.summary()
    assert rows == N_ROWS
    assert summary["completed"] == summary["n_shards"]
    assert summary["duplicates"] == 0


def test_all_endpoints_dead_raises(corpus, tracker):
    rec, idx = corpus
    # nothing listening on this port
    import dmlc_core_tpu.tracker.protocol as proto

    port = proto.find_free_port("127.0.0.1", 20000, 30000)
    c = DsServeBatches(
        f"dsserve://127.0.0.1:{port}{_uri(rec, idx)}", _spec(),
        mode="lease", connect_timeout=0.5,
    )
    with pytest.raises(Error, match="every dsserve endpoint failed"):
        for _ in c:
            pass
    c.close()


# -- dmlc-submit --dsserve ----------------------------------------------------

DSSERVE_PAYLOAD = """\
import hashlib, os, sys
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.dsserve import DsServeBatches
from dmlc_core_tpu.staging.batcher import BatchSpec

spec = BatchSpec(batch_size={batch}, layout="ell", max_nnz={k})
src = DsServeBatches(
    "dsserve://" + os.environ["DMLC_DSSERVE"] + {uri!r}, spec,
    mode="lease",
)
rows = sum(b.n_valid for b in src)
src.close()
print("drained", rows, flush=True)
"""


def test_submit_dsserve_tier_end_to_end(corpus, tmp_path):
    """``dmlc-submit --dsserve 2``: the local backend starts the tier
    beside the tracker, exports DMLC_DSSERVE to the payload, the
    payload drains the full corpus through it, and the tier is torn
    down with the job (clean exit via the shard-service accounting)."""
    rec, idx = corpus
    script = tmp_path / "payload.py"
    script.write_text(DSSERVE_PAYLOAD.format(
        repo=REPO, uri=_uri(rec, idx), batch=BATCH, k=K,
    ))
    env = os.environ.copy()
    env.update({
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "DMLC_RENDEZVOUS_GRACE": "1",
        "DMLC_SHARD_OVERSPLIT": "4",
    })
    for k in ("DMLC_TRACKER_URI", "DMLC_TRACKER_PORT", "DMLC_SHARD_RANK"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.tracker.submit",
         "--cluster", "local", "--num-workers", "1", "--dsserve", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    drained = [
        int(line.split()[-1])
        for line in proc.stdout.splitlines()
        if line.startswith("drained")
    ]
    assert drained == [N_ROWS]


def test_submit_dsserve_dry_run(corpus, tmp_path):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.tracker.submit",
         "--cluster", "local", "--num-workers", "1", "--dsserve", "2",
         "--dry-run", "true"],
        capture_output=True, text=True, timeout=60, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.count("dsserve worker") == 2


# -- server-side hygiene ------------------------------------------------------


def test_server_rejects_garbage_hello(corpus):
    srv = DsServeServer().start()
    try:
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            wire.send_frame(sock, wire.KIND_HELLO, {"nonsense": 1})
            kind, meta, _p, _s, _e = wire.recv_frame(sock)
            assert kind == wire.KIND_ERROR
            assert "HELLO" in meta["error"] or "config" in meta["error"]
        finally:
            sock.close()
        # the server survives a bad client: a good stream still works
        rec, idx = corpus
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", _spec(),
            mode="static",
        )
        assert sum(b.n_valid for b in c) == N_ROWS
        c.close()
    finally:
        srv.close()


def test_one_epoch_stream_guard(corpus):
    rec, idx = corpus
    srv = DsServeServer().start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", _spec(),
            mode="static",
        )
        assert sum(b.n_valid for b in c) == N_ROWS
        with pytest.raises(Error, match="one-epoch"):
            for _ in c:
                pass
        c.close()
    finally:
        srv.close()


# -- graceful retire (the autoscale scale-down path) --------------------------


def test_sigterm_graceful_retire_releases_leases_promptly(
    corpus, tmp_path, monkeypatch
):
    """SIGTERM on a dsserve worker is the GRACEFUL retire signal
    (docs/autoscale.md): the server finishes its in-flight shard, sends
    a retired EPOCH_END on every stream, RELEASES every lease it still
    holds back to the ledger and exits zero. Regression: the polite
    exit used to close the socket with the leases still held, so the
    survivor could only re-serve them after the full TTL — here the
    TTL is pinned to 30s, so a TTL-wait would blow the promptness
    assertion wide open."""
    import signal as _signal

    monkeypatch.setenv("DMLC_SHARD_OVERSPLIT", "6")
    monkeypatch.setenv("DMLC_SHARD_LEASE_TTL", "30.0")
    tr = RabitTracker("127.0.0.1", 1)
    tr.start(1)
    monkeypatch.setenv("DMLC_TRACKER_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_TRACKER_PORT", str(tr.port))
    monkeypatch.setenv("DMLC_TASK_ID", "0")
    monkeypatch.delenv("DMLC_SHARD_RANK", raising=False)
    rec, idx = corpus
    # mild fault latency stretches the drain so the SIGTERM lands
    # mid-stream with leases genuinely held (latency only — the bytes
    # are untouched, so the clean local reference still matches)
    slow_uri = (
        f"fault://latency_ms=30,spikes=200,cap=4096,seed=5{rec}"
        f"?index={idx}&shuffle=record&seed=3"
    )
    plain_uri = _uri(rec, idx)
    base_env = {
        "DMLC_TRACKER_URI": "127.0.0.1",
        "DMLC_TRACKER_PORT": str(tr.port),
    }
    retiree, ep0 = _spawn_server(tmp_path, 0, base_env)
    survivor, ep1 = _spawn_server(tmp_path, 1, base_env)
    try:
        c = DsServeBatches(
            f"dsserve://{ep0},{ep1}/{slow_uri}", _spec(), mode="lease",
        )
        shas = {}
        seen = []

        def on_slot(shard, seq, payload):
            shas.setdefault(shard, hashlib.sha256()).update(
                payload.tobytes()
            )
            seen.append(shard)
            if len(seen) == 3:  # early: both servers hold leases
                retiree.send_signal(_signal.SIGTERM)

        c.on_slot = on_slot
        t0 = time.monotonic()
        rows = sum(b.n_valid for b in c)
        elapsed = time.monotonic() - t0
        c.close()
        # prompt on BOTH axes: the retiree exits zero without waiting
        # out anything, and the drain never stalls on a TTL reclaim
        assert retiree.wait(timeout=20) == 0
        assert elapsed < 20.0, f"drain took {elapsed:.1f}s — TTL stall"
    finally:
        for p in (retiree, survivor):
            if p.poll() is None:
                p.kill()
                p.wait()
        tr.close()
    summary = tr.shards.summary()
    M = summary["n_shards"]
    assert rows == N_ROWS
    assert summary["completed"] == M
    assert summary["duplicates"] == 0  # exactly-once across the retire
    assert sorted(shas) == list(range(M))
    for i in range(M):
        p = fused.ell_batches(plain_uri, _spec(), part_index=i,
                              num_parts=M)
        _rows, sha, _slots = _drain_packed(p)
        p.close()
        assert shas[i].hexdigest() == sha, f"micro-shard {i} bytes differ"


def test_inprocess_retire_mid_drain_exactly_once(corpus, tracker):
    """``DsServeServer.retire()`` mid-drain: the retiring server stops
    taking new shards, the sibling finishes the epoch, the ledger stays
    exactly-once and the retiring flag is observable."""
    rec, idx = corpus
    s1 = DsServeServer(rank=101).start()
    s2 = DsServeServer(rank=102).start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{s1.port},127.0.0.1:{s2.port}"
            f"{_uri(rec, idx)}", _spec(), mode="lease",
        )
        seen = []

        def on_slot(shard, seq, payload):
            seen.append(shard)
            if len(seen) == 2:
                s1.retire()

        c.on_slot = on_slot
        rows = sum(b.n_valid for b in c)
        c.close()
        assert s1.retiring
        assert s2.shards_streamed >= 1  # the sibling carried the epoch
    finally:
        s1.close()
        s2.close()
    summary = tracker.shards.summary()
    assert rows == N_ROWS
    assert summary["completed"] == summary["n_shards"]
    assert summary["duplicates"] == 0


# -- zero-copy data plane ------------------------------------------------------


def test_wire_drip_feed_truncation_walk():
    """Short-read hardening: EOF at EVERY byte boundary of a SLOT frame
    raises the checked truncation Error naming the starved region
    (header / meta / payload) — never a hang, never a silent partial
    frame. The walk drip-feeds every prefix of a real frame."""
    import struct  # test-side header parsing (L015 scopes library code)

    payload = np.arange(48, dtype=np.uint8)
    with _Pipe() as (a, b):
        wire.send_frame(a, wire.KIND_SLOT, {"shard": 1}, payload, seq=2)
        frame = b""
        b.settimeout(5)
        while True:
            try:
                chunk = b.recv(4096)
            except socket.timeout:
                break
            if not chunk:
                break
            frame += chunk
            if len(frame) >= wire.HDR_BYTES + 11 + payload.nbytes:
                break
    mlen = struct.unpack("<IBBHqiIII", frame[: wire.HDR_BYTES])[6]
    assert len(frame) == wire.HDR_BYTES + mlen + payload.nbytes
    for cut in range(len(frame)):
        a2, b2 = socket.socketpair()
        try:
            a2.sendall(frame[:cut])
            a2.close()
            if cut == 0:
                # EOF before byte one is the CLEAN close, not truncation
                with pytest.raises(ConnectionError):
                    wire.recv_frame(b2)
                continue
            region = (
                "header"
                if cut < wire.HDR_BYTES
                else "meta"
                if cut < wire.HDR_BYTES + mlen
                else "payload"
            )
            with pytest.raises(Error, match=f"truncated frame {region}"):
                wire.recv_frame(b2)
        finally:
            b2.close()
    # the pooled recv-into reader shares the hardened path
    buf = np.zeros(64, dtype=np.uint8)
    a2, b2 = socket.socketpair()
    try:
        a2.sendall(frame[: wire.HDR_BYTES + mlen + 10])
        a2.close()
        with pytest.raises(Error, match="truncated frame payload"):
            wire.read_frame_into(b2, buf)
    finally:
        b2.close()


def test_slot_pool_reuse_under_live_views():
    """_SlotPool's liveness contract: a bank is re-banked only when the
    LAST view over its carve dies — a lease-buffered batch's bytes can
    never be overwritten by pool churn — and growth retires undersized
    banks instead of handing them out again."""
    import gc

    from dmlc_core_tpu.dsserve.client import _SlotPool

    pool = _SlotPool()
    assert pool.get() is None  # unsized: caller takes the alloc reader
    pool.ensure(1 << 12)
    a = pool.get()
    assert a.nbytes == 1 << 12
    assert a.ctypes.data % 4096 == 0  # page-aligned carve
    b = pool.get()
    assert pool.banks == 2
    a[:] = 7
    view = a[100:200]  # read_batch-style section alias
    del a
    gc.collect()
    c = pool.get()  # first bank still aliased by `view`: must be fresh
    assert pool.banks == 3
    c[:] = 9
    assert (view == 7).all()  # held bytes survive pool churn
    del view
    gc.collect()
    d = pool.get()  # the first bank finally recycled: no new bank
    assert pool.banks == 3
    pool.ensure(1 << 13)
    e = pool.get()
    assert e.nbytes == 1 << 13
    assert pool.banks == 4
    del b, c, d  # undersized banks retire through their finalizers
    gc.collect()
    assert pool.banks == 1
    del e


def test_adoptable_slot_predicate():
    """Shape gate for the staging pipeline's zero-copy adoption: dense
    page-aligned packed buffers qualify; unaligned, strided or
    packed-less batches take the dispatch_pack copy."""
    from dmlc_core_tpu.staging.batcher import Batch
    from dmlc_core_tpu.staging.pipeline import adoptable_slot

    mem = bytearray((1 << 13) + 4096)
    whole = np.frombuffer(mem, dtype=np.uint8)
    off = (-whole.ctypes.data) % 4096
    aligned = np.frombuffer(mem, dtype=np.uint8, count=1 << 12, offset=off)
    lab = np.zeros(4, dtype=np.float32)

    def mk(packed):
        return Batch(labels=lab, weights=lab, n_valid=4, packed=packed)

    assert adoptable_slot(mk(aligned))
    assert not adoptable_slot(mk(None))
    unaligned = np.frombuffer(
        mem, dtype=np.uint8, count=1 << 12, offset=off + 1
    )
    assert not adoptable_slot(mk(unaligned))
    assert not adoptable_slot(mk(aligned[::2]))


@pytest.mark.parametrize("transport", ["tcp", "tcp_codec", "shm"])
@pytest.mark.parametrize("path", ["fused", "generic"])
def test_transport_matrix_bit_identity(transport, path, corpus, monkeypatch):
    """The data-plane acceptance matrix: {plain TCP, TCP + adaptive
    codec (throttled so compression engages), same-host shm} ×
    {fused, generic} drains are all BIT-IDENTICAL to the local
    pipeline, and the telemetry proves which transport carried the
    slots."""
    rec, idx = corpus
    spec = _spec(overflow="error" if path == "generic" else "truncate")
    local = fused.ell_batches(_uri(rec, idx), spec)
    want = _drain_packed(local)
    local.close()
    monkeypatch.setenv(
        "DMLC_DSSERVE_SHM", "on" if transport == "shm" else "off"
    )
    if transport == "tcp_codec":
        monkeypatch.setenv("DMLC_DSSERVE_WIRE_CODEC", "zlib")
        # throttle loopback so the measured wire bandwidth makes
        # compression the winning move (no knob forces it on)
        monkeypatch.setenv("DMLC_DSSERVE_WIRE_BPS", "1000000")
    w0 = wire._BYTES_WIRE.value()
    r0 = wire._BYTES_RAW.value()
    srv = DsServeServer().start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", spec,
            mode="static",
        )
        got = _drain_packed(c)
        stats = c.io_stats()
        c.close()
    finally:
        srv.close()
    assert got == want
    slots = stats["slots"]
    if transport == "shm":
        assert stats["shm_slots"] >= 1  # the ring actually carried slots
        assert stats["shm_slots"] + stats["tcp_slots"] == slots
        assert srv.shm_slots_sent == stats["shm_slots"]
        assert stats["reconnects"] == 0  # shm never degraded the stream
    else:
        assert stats["shm_slots"] == 0
        assert stats["tcp_slots"] == slots
    if transport == "tcp_codec":
        dw = wire._BYTES_WIRE.value() - w0
        dr = wire._BYTES_RAW.value() - r0
        assert dr > 0 and dw < dr  # the adaptive codec actually engaged


def test_shm_degrade_drill_silent_tcp_fallback(corpus, monkeypatch):
    """DMLC_DSSERVE_SHM_BREAK_AFTER chaos drill: after N shm slots the
    server names a never-created segment, the client's shm_open ENOENTs,
    the endpoint silently degrades to TCP (one reconnect, sticky — no
    flap) and the resumed stream is bit-identical: exactly-once, zero
    operator action."""
    rec, idx = corpus
    spec = _spec()
    local = fused.ell_batches(_uri(rec, idx), spec)
    want = _drain_packed(local)
    local.close()
    monkeypatch.setenv("DMLC_DSSERVE_SHM_BREAK_AFTER", "3")
    # a ring deeper than the client's prefetch queue: no ring-exhausted
    # TCP fallbacks before the break, so the slot positions are exact
    monkeypatch.setenv("DMLC_DSSERVE_SHM_SLOTS", "64")
    srv = DsServeServer().start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", spec,
            mode="static",
        )
        got = _drain_packed(c)
        stats = c.io_stats()
        assert c._eps[0].shm_ok is False  # degrade is sticky
        c.close()
    finally:
        srv.close()
    assert got == want  # bit-identical despite the mid-stream break
    assert stats["shm_slots"] == 3  # the pre-break shm slots delivered
    assert stats["tcp_slots"] == want[2] - 3  # the TCP resume tail
    assert stats["reconnects"] == 1  # one degrade, never a flap loop


def test_hold_budget_backpressure_never_drops(corpus, tracker, monkeypatch):
    """A DMLC_DSSERVE_HOLD_MB budget far below one micro-shard's bytes
    still drains the epoch exactly-once: the largest holder always
    proceeds (backpressure, never drop, never a mutual-park deadlock)
    and the peak gauge records the held bytes."""
    from dmlc_core_tpu.dsserve.client import _HELD_BYTES

    rec, idx = corpus
    monkeypatch.setenv("DMLC_DSSERVE_HOLD_MB", "0.01")  # ~10 KB ceiling
    s1 = DsServeServer(rank=101).start()
    s2 = DsServeServer(rank=102).start()
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{s1.port},127.0.0.1:{s2.port}"
            f"{_uri(rec, idx)}", _spec(), mode="lease",
        )
        rows = sum(b.n_valid for b in c)
        c.close()
    finally:
        s1.close()
        s2.close()
    summary = tracker.shards.summary()
    assert rows == N_ROWS
    assert summary["completed"] == summary["n_shards"]
    assert summary["duplicates"] == 0
    assert _HELD_BYTES.value() > 0  # the peak gauge saw held bytes


def test_staging_pipeline_adopts_received_slots(corpus):
    """The tentpole end state: recv → ONE device_put. Every received
    slot is adopted straight into the transfer (dispatch_pack skipped,
    ``dsserve.slot_copies`` stays flat) because dsserve's pooled/shm
    buffers are page-aligned and liveness-tracked."""
    jax = pytest.importorskip("jax")
    from dmlc_core_tpu.staging import pipeline as pl

    rec, idx = corpus
    spec = _spec()
    copies0 = pl._SLOT_COPIES.value()
    srv = DsServeServer().start()
    try:
        src = DsServeBatches(
            f"dsserve://127.0.0.1:{srv.port}{_uri(rec, idx)}", spec,
            mode="static",
        )
        pipe = pl.StagingPipeline(src, device=jax.local_devices()[0])
        n = sum(1 for _ in pipe)
        stats = pipe.staging_stats()
        pl.drain_close(pipe, src)
    finally:
        srv.close()
    assert n > 0
    assert stats["slots_adopted"] == n  # every slot skipped the copy
    assert stats["packed_batches"] == n
    assert pl._SLOT_COPIES.value() == copies0


def test_client_discovers_endpoints_from_file(
    corpus, tracker, tmp_path, monkeypatch
):
    """DMLC_DSSERVE_FILE dynamic membership (the autoscale join path):
    a client dialed at ONE endpoint picks the second out of the
    endpoints file mid-stream and the drain stays exactly-once."""
    rec, idx = corpus
    s1 = DsServeServer(rank=101).start()
    s2 = DsServeServer(rank=102).start()
    eps = tmp_path / "endpoints.json"
    eps.write_text(json.dumps({
        "endpoints": [f"127.0.0.1:{s1.port}", f"127.0.0.1:{s2.port}"],
    }))
    monkeypatch.setenv("DMLC_DSSERVE_FILE", str(eps))
    try:
        c = DsServeBatches(
            f"dsserve://127.0.0.1:{s1.port}{_uri(rec, idx)}", _spec(),
            mode="lease",
        )
        deadline = time.monotonic() + 5
        while len(c.endpoints) < 2:
            assert time.monotonic() < deadline, "discovery never added s2"
            time.sleep(0.02)
        rows = sum(b.n_valid for b in c)
        c.close()
    finally:
        s1.close()
        s2.close()
    summary = tracker.shards.summary()
    assert rows == N_ROWS
    assert summary["completed"] == summary["n_shards"]
    assert summary["duplicates"] == 0
