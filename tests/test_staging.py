"""Staging layer: fixed-shape batching + device staging on the virtual
8-device CPU mesh (conftest sets XLA_FLAGS/JAX_PLATFORMS)."""

import numpy as np
import pytest

from dmlc_core_tpu.data.row_block import RowBlock
from dmlc_core_tpu.staging import (
    BatchSpec,
    FixedShapeBatcher,
    StagingPipeline,
    stage_batch,
)


def ragged_block(sizes, base=0):
    offset = np.zeros(len(sizes) + 1, dtype=np.int64)
    np.cumsum(sizes, out=offset[1:])
    nnz = int(offset[-1])
    return RowBlock(
        offset=offset,
        label=np.arange(base, base + len(sizes), dtype=np.float32),
        index=np.arange(nnz, dtype=np.uint64) % 16,
        value=np.linspace(1, 2, nnz, dtype=np.float32) if nnz else None,
    )


# -- ELL layout --------------------------------------------------------------

def test_ell_shapes_and_padding():
    spec = BatchSpec(batch_size=4, layout="ell", max_nnz=3)
    b = FixedShapeBatcher(spec)
    blk = ragged_block([2, 3, 1])  # 3 rows < batch_size
    batches = list(b.push(blk))
    assert batches == []
    tail = b.flush()
    assert tail.batch_size == 4 and tail.n_valid == 3
    assert tail.indices.shape == (4, 3) and tail.values.shape == (4, 3)
    np.testing.assert_array_equal(tail.nnz, [2, 3, 1, 0])
    np.testing.assert_array_equal(tail.weights, [1, 1, 1, 0])  # pad masked
    # row 0 has 2 real slots, third is zero padding
    assert tail.values[0, 2] == 0.0


def test_ell_round_trip_values():
    spec = BatchSpec(batch_size=2, layout="ell", max_nnz=4)
    b = FixedShapeBatcher(spec)
    blk = ragged_block([4, 2])
    (batch,) = list(b.push(blk))
    for i in range(2):
        row = blk[i]
        k = len(row)
        np.testing.assert_array_equal(batch.indices[i, :k], row.index)
        np.testing.assert_allclose(batch.values[i, :k], row.value)


def test_ell_truncation_policy():
    spec = BatchSpec(batch_size=1, layout="ell", max_nnz=2, overflow="truncate")
    b = FixedShapeBatcher(spec)
    (batch,) = list(b.push(ragged_block([5])))
    assert batch.nnz[0] == 2
    assert b.truncated_nnz == 3
    spec_err = BatchSpec(batch_size=1, layout="ell", max_nnz=2, overflow="error")
    with pytest.raises(Exception, match="max_nnz"):
        list(FixedShapeBatcher(spec_err).push(ragged_block([5])))


def test_streaming_remainder_carry():
    """Rows flow across block boundaries into exact-size batches."""
    spec = BatchSpec(batch_size=8, layout="ell", max_nnz=4)
    b = FixedShapeBatcher(spec)
    out = list(b.batches(iter([ragged_block([1] * 5), ragged_block([2] * 10, 5),
                               ragged_block([1] * 3, 15)])))
    assert [x.n_valid for x in out] == [8, 8, 2]
    assert b.rows_in == 18 and b.rows_out == 18
    # labels arrive in order across the whole stream
    all_labels = np.concatenate([x.labels[: x.n_valid] for x in out])
    np.testing.assert_array_equal(all_labels[:5], [0, 1, 2, 3, 4])
    np.testing.assert_array_equal(all_labels[5:15], np.arange(5, 15))


# -- dense layout ------------------------------------------------------------

def test_dense_scatter_and_duplicate_accumulate():
    spec = BatchSpec(batch_size=2, layout="dense", num_features=8)
    b = FixedShapeBatcher(spec)
    blk = RowBlock(
        offset=np.array([0, 3, 4]),
        label=np.array([1.0, 0.0], np.float32),
        index=np.array([1, 1, 5, 7], np.uint64),  # dup index in row 0
        value=np.array([0.5, 0.25, 2.0, 3.0], np.float32),
    )
    (batch,) = list(b.push(blk))
    assert batch.x.shape == (2, 8)
    assert batch.x[0, 1] == pytest.approx(0.75)  # accumulated
    assert batch.x[0, 5] == 2.0 and batch.x[1, 7] == 3.0


def test_dense_overflow_policies():
    blk = RowBlock(
        offset=np.array([0, 1]), label=np.array([1.0], np.float32),
        index=np.array([100], np.uint64), value=np.array([1.0], np.float32),
    )
    spec = BatchSpec(batch_size=1, layout="dense", num_features=8)
    b = FixedShapeBatcher(spec)
    (batch,) = list(b.push(blk))
    assert batch.x.sum() == 0 and b.truncated_nnz == 1
    spec_err = BatchSpec(
        batch_size=1, layout="dense", num_features=8, overflow="error"
    )
    with pytest.raises(Exception, match="num_features"):
        list(FixedShapeBatcher(spec_err).push(blk))


def test_binary_features_default_value_one():
    blk = RowBlock(
        offset=np.array([0, 2]), label=np.array([1.0], np.float32),
        index=np.array([3, 6], np.uint64), value=None,
    )
    spec = BatchSpec(batch_size=1, layout="dense", num_features=8)
    (batch,) = list(FixedShapeBatcher(spec).push(blk))
    assert batch.x[0, 3] == 1.0 and batch.x[0, 6] == 1.0


# -- device staging ----------------------------------------------------------

def test_stage_batch_single_device():
    import jax

    spec = BatchSpec(batch_size=4, layout="dense", num_features=8)
    b = FixedShapeBatcher(spec)
    (batch,) = list(b.push(ragged_block([2, 2, 1, 3])))
    dev = stage_batch(batch)
    assert isinstance(dev["x"], jax.Array)
    np.testing.assert_allclose(np.asarray(dev["x"]), batch.x)
    np.testing.assert_allclose(np.asarray(dev["labels"]), batch.labels)


def test_stage_batch_sharded_over_mesh():
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devices, ("data",))
    spec = BatchSpec(batch_size=16, layout="ell", max_nnz=4)
    b = FixedShapeBatcher(spec)
    (batch,) = list(b.push(ragged_block([2] * 16)))
    dev = stage_batch(batch, mesh=mesh)
    x = dev["values"]
    assert x.shape == (16, 4)
    # batch dim sharded 8 ways, feature dim replicated
    assert len(x.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in x.addressable_shards}
    assert shard_shapes == {(2, 4)}
    np.testing.assert_allclose(np.asarray(x), batch.values)


def test_staging_pipeline_end_to_end():
    spec = BatchSpec(batch_size=8, layout="dense", num_features=16)
    batcher = FixedShapeBatcher(spec)
    blocks = [ragged_block([2] * 6, base=6 * i) for i in range(5)]  # 30 rows
    pipe = StagingPipeline(batcher.batches(iter(blocks)), depth=2)
    seen_rows = 0
    labels = []
    for dev in pipe:
        arr = np.asarray(dev["labels"])
        w = np.asarray(dev["weights"])
        labels.extend(arr[w > 0].tolist())
        seen_rows += int((w > 0).sum())
    assert seen_rows == 30
    assert pipe.rows_staged == 30 and pipe.batches_staged == 4
    stats = pipe.throughput()
    assert stats["rows"] == 30 and stats["rows_per_sec"] > 0
    # per-stage breakdown (VERDICT r4 weak #1), with the dispatch split
    # into pack/put by the dispatch ring (ISSUE 3): every phase ticked
    # and reported both on the attribute and through throughput()
    assert set(pipe.stage_seconds) == {
        "host_pull", "dispatch_pack", "dispatch_put",
        "dispatch_slot_wait", "stage_dispatch", "transfer_wait",
    }
    assert all(v >= 0 for v in pipe.stage_seconds.values())
    assert pipe.stage_seconds["stage_dispatch"] > 0
    assert pipe.stage_seconds["stage_dispatch"] == pytest.approx(
        pipe.stage_seconds["dispatch_pack"]
        + pipe.stage_seconds["dispatch_put"]
    )
    assert stats["secs_stage_dispatch"] == (
        pipe.stage_seconds["stage_dispatch"]
    )
    # packed single-DMA path engaged (the generic batcher packs too now)
    st = pipe.staging_stats()
    assert st["packed_batches"] == 4 and st["per_array_batches"] == 0
    assert st["device_puts"] == 4  # ONE put per batch, not one per array
    assert st["packed_shard_dma"] is False
    assert pipe.io_stats()["staging"]["packed_batches"] == 4
    pipe.close()


def test_pipeline_rejects_shallow_ring():
    """The ring contract counts every concurrent holding point:
    1 in the producer thread + prefetch queued + 1 on the transfer
    thread + depth in the device queue + 1 being consumed
    (= prefetch + depth + 3)."""

    class _RingStream:
        ring_slots = 5

        def __iter__(self):  # pragma: no cover — rejected before use
            return iter(())

    with pytest.raises(Exception, match="ring has 5 slots"):
        StagingPipeline(_RingStream(), depth=2, prefetch=1)
    ok = StagingPipeline(_RingStream(), depth=1, prefetch=1)
    ok.close()


def test_dense_wrapped_negative_index_is_overflow():
    """A parsed '-5' feature wraps to 2^64-5; it must count as overflow,
    not scatter into column D-5."""
    blk = RowBlock(
        offset=np.array([0, 1]), label=np.array([1.0], np.float32),
        index=np.array([np.uint64(2**64 - 5)], np.uint64),
        value=np.array([3.0], np.float32),
    )
    spec = BatchSpec(batch_size=1, layout="dense", num_features=8)
    b = FixedShapeBatcher(spec)
    (batch,) = list(b.push(blk))
    assert batch.x.sum() == 0 and b.truncated_nnz == 1
    spec_err = BatchSpec(
        batch_size=1, layout="dense", num_features=8, overflow="error"
    )
    with pytest.raises(Exception):
        list(FixedShapeBatcher(spec_err).push(blk))


def test_ell_index_dtype_overflow_guard():
    """Feature ids beyond int32 must not silently wrap in the ELL array."""
    blk = RowBlock(
        offset=np.array([0, 2]), label=np.array([1.0], np.float32),
        index=np.array([3, 3_000_000_000], np.uint64),
        value=np.array([1.0, 2.0], np.float32),
    )
    spec = BatchSpec(batch_size=1, layout="ell", max_nnz=4)
    b = FixedShapeBatcher(spec)
    (batch,) = list(b.push(blk))
    assert b.truncated_nnz == 1
    assert batch.nnz[0] == 1
    assert (batch.indices >= 0).all()
    spec_err = BatchSpec(
        batch_size=1, layout="ell", max_nnz=4, overflow="error"
    )
    with pytest.raises(Exception, match="does not fit"):
        list(FixedShapeBatcher(spec_err).push(blk))


# -- fault injection (SURVEY §7 step 7: fault-injection producers) -----------

class _FaultyProducer:
    """Yields ``good`` real batches then raises — the disk/parse failure
    modes (IO error, corrupt shard) surfacing mid-epoch inside the
    prefetch thread."""

    def __init__(self, good: int, exc: Exception):
        self.good = good
        self.exc = exc
        self.closed = False

    def __iter__(self):
        spec = BatchSpec(batch_size=2, layout="ell", max_nnz=3)
        b = FixedShapeBatcher(spec)
        for i in range(self.good):
            for out in b.push(ragged_block([1, 2], base=2 * i)):
                yield out
        raise self.exc

    def close(self):
        self.closed = True


@pytest.mark.jax
def test_pipeline_propagates_producer_fault_midstream():
    """A producer raising mid-epoch (after real batches staged) must
    surface THAT exception to the consumer — not hang the prefetch
    thread, not truncate silently — and the pipeline must still close."""
    boom = OSError("disk died mid-shard")
    prod = _FaultyProducer(good=3, exc=boom)
    pipe = StagingPipeline(prod)
    staged = []
    with pytest.raises(OSError, match="disk died"):
        for dev in pipe:
            staged.append(np.asarray(dev["labels"]))
    # batches already handed out arrived intact; the batch still in
    # flight behind the fault is dropped WITH the exception (the epoch is
    # poisoned — consumers restart from checkpoint, never trust a tail)
    assert len(staged) >= 2
    for i, lab in enumerate(staged):
        np.testing.assert_array_equal(lab, [2 * i, 2 * i + 1])
    pipe.close()  # must not wedge on the dead prefetch thread
    prod.close()


@pytest.mark.jax
def test_pipeline_fault_before_first_batch():
    boom = ValueError("corrupt header")
    pipe = StagingPipeline(_FaultyProducer(good=0, exc=boom))
    with pytest.raises(ValueError, match="corrupt header"):
        next(iter(pipe))
    pipe.close()


@pytest.mark.jax
def test_pipeline_abandoned_mid_epoch_closes_clean():
    """A consumer that stops pulling (early stopping, crash-unwind) and
    closes must not deadlock against a full prefetch queue."""
    spec = BatchSpec(batch_size=2, layout="ell", max_nnz=3)
    b = FixedShapeBatcher(spec)
    blocks = [ragged_block([1, 2], base=2 * i) for i in range(50)]

    def gen():
        for blk in blocks:
            yield from b.push(blk)

    pipe = StagingPipeline(gen())
    it = iter(pipe)
    next(it)  # stage one batch, then abandon with the queue primed
    assert pipe.close() is True  # clean join: safe to tear down sources
    assert pipe.close_timed_out is False


@pytest.mark.jax
def test_pipeline_close_does_not_wedge_on_stalled_producer():
    """close() while the upstream producer is stalled in
    uninterruptible IO must return promptly (bounded join + orphaned
    daemon thread), not block for the stall's duration."""
    import time

    spec = BatchSpec(batch_size=2, layout="ell", max_nnz=3)

    class _Stalled:
        def __iter__(self):
            b = FixedShapeBatcher(spec)
            yield from b.push(ragged_block([1, 2]))
            time.sleep(30)  # un-interruptible upstream stall
            yield from b.push(ragged_block([1, 2]))  # pragma: no cover

    pipe = StagingPipeline(_Stalled())
    it = iter(pipe)
    next(it)
    time.sleep(0.2)  # let the producer enter the stall
    t0 = time.perf_counter()
    clean = pipe.close()
    assert time.perf_counter() - t0 < 5.0
    # the orphaned producer is reported, so the caller knows NOT to tear
    # down mmap-backed sources the thread may still be reading
    assert clean is False
    assert pipe.close_timed_out is True
    # the abandoned iterator must also see a clean end, not a hang
    assert list(it) == []


class _ClosableSource:
    """Iterable batch source recording whether close() was called."""

    def __init__(self, batches):
        self._batches = batches
        self.closed = False

    def __iter__(self):
        return iter(self._batches)

    def close(self):
        self.closed = True


@pytest.mark.jax
def test_drain_close_closes_source_on_clean_join():
    from dmlc_core_tpu.staging import drain_close

    spec = BatchSpec(batch_size=2, layout="ell", max_nnz=3)
    b = FixedShapeBatcher(spec)
    src = _ClosableSource(list(b.push(ragged_block([1, 2]))))
    pipe = StagingPipeline(src)
    for _ in pipe:
        pass
    assert drain_close(pipe, src) is True
    assert src.closed is True


@pytest.mark.jax
def test_drain_close_defers_source_on_timed_out_join():
    """close_timed_out honored: an orphaned producer thread may still be
    reading the source's (mmap-backed) buffers — drain_close must NOT
    free them under it."""
    import time

    spec = BatchSpec(batch_size=2, layout="ell", max_nnz=3)

    class _StalledSource(_ClosableSource):
        def __iter__(self):
            b = FixedShapeBatcher(spec)
            yield from b.push(ragged_block([1, 2]))
            time.sleep(30)  # un-interruptible upstream stall
            yield from b.push(ragged_block([1, 2]))  # pragma: no cover

    from dmlc_core_tpu.staging import drain_close

    src = _StalledSource([])
    pipe = StagingPipeline(src)
    it = iter(pipe)
    next(it)
    time.sleep(0.2)  # let the producer enter the stall
    assert drain_close(pipe, src) is False
    assert pipe.close_timed_out is True
    assert src.closed is False, "source freed under a live reader thread"
