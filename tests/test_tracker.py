"""Tracker tests: topology math, wire rendezvous with real sockets
(multi-node-without-a-cluster, the reference's §4 test pattern taken one
level deeper: actual TCP rank assignment + peer wiring in-process),
backend command builders, and the dmlc-submit CLI."""

import os
import random
import socket
import sys
import threading
import time

import pytest

from dmlc_core_tpu.tracker import topology
from dmlc_core_tpu.tracker.client import RabitWorker
from dmlc_core_tpu.tracker.tracker import RabitTracker
from dmlc_core_tpu.tracker import opts as tracker_opts
from dmlc_core_tpu.tracker.backends import (
    get_backend,
    kubernetes as kube_backend,
    mesos as mesos_backend,
    mpi as mpi_backend,
    slurm as slurm_backend,
    ssh as ssh_backend,
    tpu_pod,
)
from dmlc_core_tpu.tracker.launcher import derive_role


# -- topology ----------------------------------------------------------------

@pytest.mark.parametrize("n", [1, 2, 3, 4, 7, 8, 16, 33, 100])
def test_tree_structure(n):
    tree_map, parent_map = topology.get_tree(n)
    assert parent_map[0] == -1
    for r in range(1, n):
        p = parent_map[r]
        assert 0 <= p < r
        assert r in tree_map[p] and p in tree_map[r]


@pytest.mark.parametrize("n", [1, 2, 3, 4, 7, 8, 16, 33, 100])
def test_ring_is_hamiltonian(n):
    tree_map, parent_map = topology.get_tree(n)
    ring = topology.get_ring(tree_map, parent_map)
    seen = [0]
    cur = 0
    for _ in range(n - 1):
        cur = ring[cur][1]
        seen.append(cur)
    assert sorted(seen) == list(range(n))
    assert ring[seen[-1]][1] == 0  # closes the loop
    for r in range(n):
        prev, nxt = ring[r]
        assert ring[prev][1] == r and ring[nxt][0] == r


@pytest.mark.parametrize("n", [1, 2, 5, 8, 16, 33])
def test_link_map_ring_order(n):
    """After relabeling, the ring is 0 → 1 → ... → n-1 → 0."""
    _tree, parent, ring = topology.get_link_map(n)
    for r in range(n):
        assert ring[r] == ((r - 1) % n, (r + 1) % n)
    assert parent[0] == -1


def _fuzzed_ns(count=40, lo=1, hi=311, seed=0xD31C):
    """Deterministic fuzz draw for the topology property tests: a
    seeded spread over world sizes including the awkward shapes
    (1, 2, powers of two ± 1) plus random fill."""
    rng = random.Random(seed)
    ns = {1, 2, 3, 4, 5, 7, 8, 9, 15, 16, 17, 31, 32, 33, 63, 64, 65}
    while len(ns) < count:
        ns.add(rng.randint(lo, hi))
    return sorted(ns)


def test_property_ring_is_hamiltonian_fuzzed():
    """For fuzzed n: following ring-next from 0 visits every rank
    exactly once and closes the loop, and prev/next are inverses —
    get_ring is a Hamiltonian cycle over the tree."""
    for n in _fuzzed_ns():
        tree_map, parent_map = topology.get_tree(n)
        ring = topology.get_ring(tree_map, parent_map)
        assert sorted(ring) == list(range(n))
        seen = []
        cur = 0
        for _ in range(n):
            seen.append(cur)
            cur = ring[cur][1]
        assert cur == 0, f"n={n}: ring does not close at 0"
        assert sorted(seen) == list(range(n)), f"n={n}: not Hamiltonian"
        for r in range(n):
            prev, nxt = ring[r]
            assert ring[prev][1] == r and ring[nxt][0] == r, (
                f"n={n}: prev/next not inverse at rank {r}"
            )


def test_property_link_map_relabel_is_bijection_fuzzed():
    """For fuzzed n: get_link_map's relabeling is a bijection on
    range(n) and an isomorphism — the relabeled tree/parent/ring are
    exactly the original maps with every rank pushed through one
    permutation (ring position, so relabeled ring-next is rank+1)."""
    for n in _fuzzed_ns():
        tree_map, parent_map = topology.get_tree(n)
        ring = topology.get_ring(tree_map, parent_map)
        tree2, parent2, ring2 = topology.get_link_map(n)
        # the relabeling is ring position: reconstruct it independently
        relabel = {}
        cur = 0
        for pos in range(n):
            relabel[cur] = pos
            cur = ring[cur][1]
        # bijection on range(n), and every returned map is keyed by it
        assert sorted(relabel) == list(range(n))
        assert sorted(relabel.values()) == list(range(n))
        assert sorted(tree2) == list(range(n))
        assert sorted(parent2) == list(range(n))
        assert sorted(ring2) == list(range(n))
        # isomorphism: edges/parents/ring all commute with the relabel
        for r in range(n):
            assert sorted(tree2[relabel[r]]) == sorted(
                relabel[x] for x in tree_map[r]
            ), f"n={n}: tree edges not preserved at rank {r}"
            if r == 0:
                assert parent2[relabel[0]] == -1
            else:
                assert parent2[relabel[r]] == relabel[parent_map[r]]
            a, b = ring[r]
            assert ring2[relabel[r]] == (relabel[a], relabel[b])
            assert ring2[relabel[r]] == (
                (relabel[r] - 1) % n,
                (relabel[r] + 1) % n,
            ), f"n={n}: relabeled ring not 0..n-1 order"


def test_property_ring_shares_tree_edges_fuzzed():
    """For fuzzed n: the edges the reference share-ring algorithm
    (find_share_ring, tracker.py:193-211) guarantees land on tree
    links actually do — every internal node's ring-next is its FIRST
    child (the DFS descends before it walks), and the wrap-around edge
    (last ring position → root) is the root's last child because the
    last subtree is traversed in reverse. So the ring shares at least
    (#internal nodes + 1) edges with the tree."""
    for n in _fuzzed_ns():
        if n < 2:
            continue
        tree_map, parent_map = topology.get_tree(n)
        ring = topology.get_ring(tree_map, parent_map)
        ring_edges = {frozenset((r, ring[r][1])) for r in range(n)}
        # every internal node starts its DFS sub-order [v, c1, ...]:
        # {v, first child} stays consecutive through concatenation AND
        # the last-child reversal (reversal flips direction, not
        # adjacency), so it must be a ring edge
        must_share = set()
        for v in range(n):
            children = [x for x in tree_map[v] if x != parent_map[v]]
            if children:
                must_share.add(frozenset((v, children[0])))
        # the global order ends at the root's LAST child (its reversed
        # sub-order ends with the child itself), so the wrap-around
        # edge is the tree edge {root, last child}
        last = ring[0][0]
        assert parent_map[last] == 0, (
            f"n={n}: wrap-around rank {last} is not a root child"
        )
        must_share.add(frozenset((0, last)))
        missing = must_share - ring_edges
        assert not missing, (
            f"n={n}: reference-guaranteed shared edges missing from "
            f"the ring: {sorted(tuple(e) for e in missing)}"
        )
        tree_edges = {
            frozenset((r, x)) for r in range(n) for x in tree_map[r]
        }
        shared = ring_edges & tree_edges
        assert len(shared) >= len(must_share), (
            f"n={n}: only {len(shared)} ring edges shared with the tree"
        )


# -- rendezvous over real sockets -------------------------------------------

def run_workers(tracker, n, jobid_fn=lambda i: str(i), barrier_links=True):
    results = [None] * n
    errors = []

    def one(i):
        try:
            w = RabitWorker("127.0.0.1", tracker.port, jobid=jobid_fn(i))
            rank = w.start(world_size=n if i == 0 else -1)
            # links wired before shutdown so the graph is complete
            results[i] = (rank, w.parent, w.world_size,
                          sorted(w.links), w.ring_prev, w.ring_next)
            w.shutdown()
        except Exception as e:  # noqa: BLE001
            errors.append((i, e))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    return results


@pytest.mark.parametrize("n", [1, 2, 4, 7])
def test_rendezvous_assigns_unique_ranks_and_wires_links(n):
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)
    results = run_workers(tracker, n)
    tracker.join()
    tracker.close()
    ranks = sorted(r[0] for r in results)
    assert ranks == list(range(n))
    for rank, parent, world, links, rprev, rnext in results:
        assert world == n
        expected = set(topology.get_link_map(n)[0][rank])
        if rprev not in (-1, rank):
            expected.add(rprev)
        if rnext not in (-1, rank):
            expected.add(rnext)
        assert set(links) == expected, (rank, links, expected)


def test_print_relay_and_recover():
    tracker = RabitTracker("127.0.0.1", 2)
    tracker.start(2)

    w0 = RabitWorker("127.0.0.1", tracker.port, jobid="0")
    w1 = RabitWorker("127.0.0.1", tracker.port, jobid="1")
    t1 = threading.Thread(target=lambda: w1.start(world_size=-1))
    t1.start()
    r0 = w0.start(world_size=2)
    t1.join(timeout=15)
    w0.log("hello from worker")
    time.sleep(0.2)
    assert any("hello from worker" in m for m in tracker.messages)

    # simulate a restart of worker 0: it recovers with its previous rank,
    # and the surviving peer (having seen its link die) re-rendezvouses too
    # so the tracker can broker the reconnection (reference recover
    # contract, tracker.py:290-292,312-316)
    r1 = w1.rank
    w0.close()
    dead = w1.links.pop(r0, None)
    if dead is not None:
        dead.close()
    w0b = RabitWorker("127.0.0.1", tracker.port, jobid="0")
    got = {}
    t_recover = threading.Thread(
        target=lambda: got.setdefault("w1", w1.start(recover_rank=r1))
    )
    t_recover.start()
    got["w0"] = w0b.start(recover_rank=r0)
    t_recover.join(timeout=15)
    assert got["w0"] == r0 and got["w1"] == r1
    assert r0 in w1.links and r1 in w0b.links  # link re-wired
    w0b.shutdown()
    w1.shutdown()
    tracker.join()
    tracker.close()


def test_tracker_worker_envs():
    tracker = RabitTracker("127.0.0.1", 1)
    envs = tracker.worker_envs()
    assert envs["DMLC_TRACKER_URI"] == "127.0.0.1"
    assert isinstance(envs["DMLC_TRACKER_PORT"], int)
    tracker.close()


def test_await_peer_links_times_out_on_half_dead_peer(monkeypatch):
    """Regression: _await_peer_links used to block forever on a peer
    that connects but never identifies (and on one that never dials at
    all). The shared deadline must fail the worker loudly and leave it
    retryable — listener closed, no half-wired links kept."""
    from dmlc_core_tpu.tracker.protocol import make_listener

    monkeypatch.setenv("DMLC_LINK_WAIT_TIMEOUT", "0.5")
    w = RabitWorker("127.0.0.1", 1, jobid="x")
    w.rank = 0
    w._listener = make_listener("127.0.0.1", 0)
    port = w._listener.getsockname()[1]
    # a half-dead peer: dials in, sends NOTHING
    mute = socket.create_connection(("127.0.0.1", port), timeout=5)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="timed out .* incoming peer"):
        w._await_peer_links(2)  # the second peer never even dials
    assert time.monotonic() - t0 < 5, "deadline not enforced"
    assert w.links == {}  # the unidentified accept was not kept
    assert w._listener.fileno() < 0  # listener closed: start() retryable
    mute.close()
    w.close()


def test_worker_shutdown_and_close_are_idempotent():
    """Regression: double shutdown() used to re-send cmd=shutdown (a
    tracker protocol violation) and double close() could raise on the
    already-closed listener. Both must be safe no-ops the second time —
    teardown paths race (atexit + explicit close)."""
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    w = RabitWorker("127.0.0.1", tracker.port, jobid="0")
    assert w.start(world_size=1) == 0
    w.shutdown()
    w.shutdown()  # second signal: no duplicate cmd, no raise
    w.close()  # close after shutdown: no raise
    w.close()
    assert w.links == {} and w._listener is None
    tracker.join()
    tracker.close()


def test_peer_connect_timeout_is_explicit(monkeypatch):
    """Regression: the peer dial rides $DMLC_PEER_CONNECT_TIMEOUT — a
    worker constructed under the knob carries it, and connect_peer
    enforces the deadline on the identify send as well as the dial (a
    listener that accepts but never reads must not wedge the dialer)."""
    from dmlc_core_tpu.tracker.protocol import connect_peer, make_listener

    monkeypatch.setenv("DMLC_PEER_CONNECT_TIMEOUT", "2.5")
    w = RabitWorker("127.0.0.1", 1, jobid="x")
    assert w.connect_timeout == 2.5
    lst = make_listener("127.0.0.1", 0, backlog=1)
    port = lst.getsockname()[1]
    sock = connect_peer("127.0.0.1", port, 3, timeout=2.5)
    # wired links are handed over in blocking mode: consumers (the
    # collective engine) set their own per-op deadlines
    assert sock.gettimeout() is None
    peer, _ = lst.accept()
    assert FramedSocket(peer).recv_int() == 3  # identified with our rank
    sock.close()
    peer.close()
    lst.close()


# -- hostile clients: the accept loop must survive and finish the job --------

from dmlc_core_tpu.tracker.protocol import MAGIC, FramedSocket


def _raw_conn(port):
    return socket.create_connection(("127.0.0.1", port), timeout=10)


def _handshake(port, rank=-1, world=-1, jobid="NULL", cmd="start"):
    fs = FramedSocket(_raw_conn(port))
    fs.send_int(MAGIC)
    assert fs.recv_int() == MAGIC
    fs.send_int(rank)
    fs.send_int(world)
    fs.send_str(jobid)
    fs.send_str(cmd)
    return fs


def test_tracker_survives_garbage_and_truncated_clients():
    """Fuzzed/garbage/truncated connections are dropped; the real job
    still completes (reference dies on any of these,
    tracker.py:293-311)."""
    tracker = RabitTracker("127.0.0.1", 2)
    tracker.start(2)

    # 1. raw garbage bytes (bad magic)
    c = _raw_conn(tracker.port)
    c.sendall(b"\xde\xad\xbe\xef" * 4)
    c.close()
    # 2. truncated handshake: magic then EOF
    c = _raw_conn(tracker.port)
    c.sendall((MAGIC).to_bytes(4, "little"))
    c.close()
    # 3. valid framing, unknown command
    fs = _handshake(tracker.port, cmd="frobnicate")
    fs.close()
    # 4. shutdown from an invalid rank
    fs = _handshake(tracker.port, rank=99, cmd="shutdown")
    fs.close()
    # 5. negative string length in the jobid frame
    c = _raw_conn(tracker.port)
    c.sendall((MAGIC).to_bytes(4, "little"))
    c.recv(4)
    c.sendall((0).to_bytes(4, "little") * 2)
    c.sendall((-5).to_bytes(4, "little", signed=True))
    c.close()

    results = run_workers(tracker, 2)
    tracker.join()
    tracker.close()
    assert sorted(r[0] for r in results) == [0, 1]


def test_tracker_rejects_goodset_outside_neighbors():
    """A client reporting links outside its neighbor set is dropped
    (ProtocolError, not AssertionError), its rank is returned to the
    pool, and a fresh worker can still claim it."""
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)

    fs = _handshake(tracker.port, world=1)
    rank = fs.recv_int()
    assert rank == 0
    fs.recv_int()  # parent
    fs.recv_int()  # world
    n_tree = fs.recv_int()
    for _ in range(n_tree):
        fs.recv_int()
    fs.recv_int()  # ring prev
    fs.recv_int()  # ring next
    # lie: claim a wired link to rank 77 (not a neighbor)
    fs.send_int(1)
    fs.send_int(77)
    # tracker must drop this connection rather than die
    fs.sock.settimeout(10)
    try:
        data = fs.sock.recv(4)
    except (ConnectionResetError, OSError):
        data = b""
    assert data == b""  # server closed on us
    fs.close()

    # the leaked rank is reusable: a well-behaved worker finishes the job
    w = RabitWorker("127.0.0.1", tracker.port, jobid="fresh")
    assert w.start(world_size=-1) == 0
    w.shutdown()
    tracker.join()
    tracker.close()


def test_tracker_batch_survives_death_mid_brokering():
    """n=2: one client dies right after receiving its rank; the other
    worker must still be assigned, and a replacement worker claims the
    leaked rank and wires the peer link (failure-atomic batch)."""
    tracker = RabitTracker("127.0.0.1", 2)
    tracker.start(2)

    survivor = RabitWorker("127.0.0.1", tracker.port, jobid="good")
    state = {}
    t = threading.Thread(
        target=lambda: state.setdefault("rank", survivor.start(world_size=2))
    )
    t.start()
    time.sleep(0.2)
    # hostile half of the batch: handshake, then vanish before brokering
    fs = _handshake(tracker.port, jobid="bad")
    fs.recv_int()  # rank arrives -> assignment in progress
    fs.close()

    # survivor gets its rank but blocks waiting for its dead peer;
    # a replacement worker picks up the leaked rank and wires the link
    replacement = RabitWorker("127.0.0.1", tracker.port, jobid="bad2")
    r2 = replacement.start(world_size=-1)
    t.join(timeout=20)
    assert not t.is_alive(), "survivor never finished wiring"
    ranks = {state["rank"], r2}
    assert ranks == {0, 1}
    assert r2 in survivor.links and state["rank"] in replacement.links
    survivor.shutdown()
    replacement.shutdown()
    tracker.join()
    tracker.close()


def test_pending_worker_unblocked_by_recover():
    """The batch trigger must re-fire when a recover shrinks the free-rank
    pool: two hostile clients leak both ranks, a fresh worker waits in
    pending, then a recover claims one rank directly — the pending worker
    must immediately get the other."""
    tracker = RabitTracker("127.0.0.1", 2)
    tracker.start(2)

    # two hostile clients: handshake, receive rank, vanish → both ranks
    # leak. Read the ranks CONCURRENTLY: with parallel handshakes the
    # tracker may assign either client first, and neighbor sessions are
    # serialized — a sequential read of f1-then-f2 deadlocks against an
    # f2-first assignment order.
    f1 = _handshake(tracker.port, world=2, jobid="h1")
    f2 = _handshake(tracker.port, jobid="h2")

    def leak(fs):
        fs.recv_int()
        fs.close()

    leakers = [threading.Thread(target=leak, args=(f,)) for f in (f1, f2)]
    for t in leakers:
        t.start()
    for t in leakers:
        t.join(timeout=15)
    assert not any(t.is_alive() for t in leakers)
    time.sleep(0.3)

    fresh = RabitWorker("127.0.0.1", tracker.port, jobid="fresh")
    state = {}
    t = threading.Thread(
        target=lambda: state.setdefault("rank", fresh.start(world_size=-1))
    )
    t.start()
    time.sleep(0.3)  # fresh is parked in pending (1 waiting, 2 free ranks)
    recoverer = RabitWorker("127.0.0.1", tracker.port, jobid="rec")
    r_rec = recoverer.start(recover_rank=1)
    t.join(timeout=20)
    assert not t.is_alive(), "pending worker was never assigned"
    assert {state["rank"], r_rec} == {0, 1}
    fresh.shutdown()
    recoverer.shutdown()
    tracker.join()
    tracker.close()


def test_tracker_drops_slow_loris_client():
    """A client that connects and stalls must be timed out, not allowed
    to wedge the single-threaded accept loop."""
    tracker = RabitTracker("127.0.0.1", 2, client_timeout=1.0)
    tracker.start(2)
    stall = _raw_conn(tracker.port)  # connects, never sends a byte
    results = run_workers(tracker, 2)
    tracker.join()
    tracker.close()
    stall.close()
    assert sorted(r[0] for r in results) == [0, 1]


def test_stalling_client_does_not_serialize_rendezvous():
    """r3 weak #5: one slow-but-alive client inside brokering stalled
    every other worker (serial accept loop). Now sessions run
    concurrently, serialized only between direct topology neighbors: a
    staller holding rank 0 of a 12-node job must delay ONLY its
    neighborhood — workers whose full neighbor set is far from rank 0
    (ranks 7, 8, 9 under the n=12 tree+ring) complete rendezvous,
    links wired, while the staller is still mid-stall."""
    n = 12
    tracker = RabitTracker("127.0.0.1", n, client_timeout=8.0)
    tracker.start(n)

    # staller: claims rank 0 (cmd=start, explicit rank), reads its
    # topology frames, then goes silent inside the brokering loop
    stall = _handshake(tracker.port, rank=0, world=n, jobid="stall")
    assert stall.recv_int() == 0  # rank echo
    stall.recv_int()  # parent
    stall.recv_int()  # world
    n_tree = stall.recv_int()
    for _ in range(n_tree):
        stall.recv_int()
    stall.recv_int()  # ring prev
    stall.recv_int()  # ring next
    # ... and now it stalls: no ngood report, session thread blocked

    t0 = time.time()
    done_at = {}
    workers = []

    def one(i):
        w = RabitWorker("127.0.0.1", tracker.port, jobid=f"w{i}")
        rank = w.start(world_size=-1)
        done_at[rank] = time.time() - t0
        workers.append(w)

    threads = [
        threading.Thread(target=one, args=(i,)) for i in range(n - 1)
    ]
    for t in threads:
        t.start()

    # the far-from-staller workers must finish while the staller is
    # still alive inside its session (client_timeout 8s; give them 6s).
    # Which exact ranks wire first depends on session order; the
    # invariant is that a NONTRIVIAL set completes instead of zero (the
    # r3 serial tracker wedged the whole pod here), and none of them is
    # a direct topology neighbor of the staller ({1, 2, 11}).
    deadline = time.time() + 6.0
    while time.time() < deadline and len(done_at) < 3:
        time.sleep(0.05)
    early = dict(done_at)
    assert len(early) >= 3, (
        f"only {len(early)} workers finished behind the staller: {early}"
    )
    assert all(t < 6.0 for t in early.values()), early
    assert not {1, 2, 11} & set(early), early

    # the staller times out (client_timeout) and its rank returns to the
    # pool; a replacement worker (the supervisor-relaunch story) claims
    # it, after which the whole job completes
    def replacement():
        # retried: until the staller's session times out, rank 0 is
        # still reserved and the tracker rejects extra workers with
        # "no free rank left" (same as the serial tracker)
        for _ in range(40):
            w = RabitWorker("127.0.0.1", tracker.port, jobid="relaunch")
            try:
                rank = w.start(world_size=-1)
            except (ConnectionError, OSError):
                time.sleep(0.5)
                continue
            done_at[rank] = time.time() - t0
            workers.append(w)
            return

    rt = threading.Thread(target=replacement)
    rt.start()
    for t in threads:
        t.join(timeout=30)
    rt.join(timeout=30)
    assert not rt.is_alive() and not any(t.is_alive() for t in threads)
    assert sorted(done_at) == list(range(n))
    for w in workers:
        w.shutdown()
    stall.close()
    tracker.join()  # all n shutdowns seen: the state thread exits
    tracker.close()


@pytest.mark.slow
def test_pod_scale_rendezvous_64_workers():
    """64 workers rendezvous concurrently (in-process pod-scale smoke):
    unique ranks, every tree+ring link wired, clean shutdown. The r3
    serial tracker brokered these one at a time; the broker pool runs
    non-adjacent sessions in parallel."""
    n = 64
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)
    t0 = time.time()
    results = run_workers(tracker, n)
    elapsed = time.time() - t0
    tracker.join()
    tracker.close()
    assert sorted(r[0] for r in results) == list(range(n))
    tree_map, _parent, _ring = topology.get_link_map(n)
    for rank, _parent_r, world, links, rprev, rnext in results:
        assert world == n
        expected = set(tree_map[rank])
        if rprev not in (-1, rank):
            expected.add(rprev)
        if rnext not in (-1, rank):
            expected.add(rnext)
        assert set(links) == expected, (rank, links, expected)
    # not a benchmark, but a 64-node rendezvous that takes minutes means
    # the brokering serialized somewhere it shouldn't
    assert elapsed < 60, f"rendezvous took {elapsed:.1f}s"


@pytest.mark.slow
def test_pod_scale_drill_supervisor_with_failures():
    """VERDICT r4 #8: the 64-worker rendezvous run UNDER the Supervisor
    with 2 injected worker deaths (ungraceful close after the
    rendezvous settles, no shutdown). The rabit recover contract plays
    out in full: relaunched attempts reclaim their previous ranks,
    NEIGHBOR survivors detect their dead link sockets and re-enter
    rendezvous (start(recover_rank=own)) so the tracker can broker the
    re-wiring, the job completes, and wall-clock stays bounded — the
    broker pool, recover path, and Supervisor compose at pod scale."""
    import select
    import socket as socket_mod

    n = 64
    die_once = {7, 23}
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)

    from dmlc_core_tpu.tracker.supervisor import Supervisor

    lock = threading.Lock()
    ranks_first = {}  # task_id -> rank obtained on the doomed attempt
    ranks_final = [None] * n
    healed = []  # task_ids that re-rendezvoused after a dead link
    # phase gates: deaths happen after the FULL rendezvous settles (a
    # close racing a peer's link-accept would just lose that link inside
    # initial wiring); shutdowns happen only after nobody scans links
    # anymore (a shutdown's closes would read as deaths otherwise)
    started = {"count": 0}
    all_started = threading.Event()
    recovered = {"count": 0}
    all_recovered = threading.Event()
    deaths = {"count": 0}
    deaths_done = threading.Event()
    watchers = {"count": 0}
    watchers_done = threading.Event()

    class _ThreadTask:
        """Popen-like handle over an in-process worker thread (the
        Supervisor's documented contract: poll/kill/wait)."""

        def __init__(self, fn):
            self._ret = None

            def body():
                try:
                    self._ret = fn()
                except Exception:  # noqa: BLE001 — exit code, not raise
                    import traceback

                    # keep the trace visible: the Supervisor only sees
                    # the exit code, and a silent assertion failure in a
                    # 64-thread drill is undiagnosable otherwise
                    traceback.print_exc()
                    self._ret = 1

            self._t = threading.Thread(target=body, daemon=True)
            self._t.start()

        def poll(self):
            if self._t.is_alive():
                return None
            return self._ret if self._ret is not None else 1

        def kill(self):
            pass  # threads can't be killed; workers here always exit

        def wait(self):
            self._t.join()
            return self.poll()

    def dead_links(w):
        """Ranks whose peer socket reached EOF (peer died)."""
        by_sock = {s: r for r, s in w.links.items()}
        try:
            readable, _, _ = select.select(list(by_sock), [], [], 0)
        except (OSError, ValueError):
            return [r for r, s in w.links.items() if s.fileno() == -1]
        out = []
        for s in readable:
            try:
                if s.recv(1, socket_mod.MSG_PEEK) == b"":
                    out.append(by_sock[s])
            except OSError:
                out.append(by_sock[s])
        return out

    def _mark(counter, event, target):
        with lock:
            counter["count"] += 1
            if counter["count"] >= target:
                event.set()

    def work(task_id: int, attempt: int) -> int:
        # jobid is stable across attempts — the tracker's recover path
        # verifies the reclaimed rank belongs to the same job
        w = RabitWorker("127.0.0.1", tracker.port, jobid=f"t{task_id}")
        recover = -1
        if attempt > 0:
            with lock:
                recover = ranks_first.get(task_id, -1)
            assert recover >= 0, (
                f"unexpected relaunch of non-doomed task {task_id}"
            )
        rank = w.start(
            world_size=n if task_id == 0 else -1, recover_rank=recover
        )
        if attempt == 0:
            _mark(started, all_started, n)
        if attempt == 0 and task_id in die_once:
            with lock:
                ranks_first[task_id] = rank
            assert all_started.wait(timeout=60)
            w.close()  # dies WITHOUT shutdown: links drop, rank orphaned
            _mark(deaths, deaths_done, len(die_once))
            return 1
        if attempt > 0:
            _mark(recovered, all_recovered, len(die_once))
        # the "training" phase: poll link health, self-heal on a dead
        # peer by re-entering rendezvous with the SAME rank (the rabit
        # recover contract this client documents in its link-wait error).
        # Scans start only after BOTH deaths have happened: a survivor
        # neighboring both dead ranks that scanned between the closes
        # would heal toward one while still reporting the other as good,
        # and the second recover session could then strand it mid-wait.
        assert deaths_done.wait(timeout=60)
        deadline = time.time() + 60
        while not all_recovered.is_set() and time.time() < deadline:
            dead = dead_links(w)
            if dead:
                for r in dead:
                    s = w.links.pop(r, None)
                    if s is not None:
                        s.close()
                got = w.start(recover_rank=rank)
                assert got == rank, (got, rank)
                with lock:
                    healed.append(task_id)
                continue
            time.sleep(0.02)
        assert all_recovered.is_set(), "relaunches never rejoined"
        ranks_final[task_id] = rank
        # nobody may shutdown while anyone still scans links: a closing
        # survivor's sockets would read as new deaths
        _mark(watchers, watchers_done, n)
        assert watchers_done.wait(timeout=60)
        w.shutdown()
        return 0

    sup = Supervisor(
        lambda tid, host, att: _ThreadTask(lambda: work(tid, att)),
        hosts=[f"pod-host-{i}" for i in range(n)],
        max_attempt=3,
        poll_interval=0.02,
    )
    t0 = time.time()
    sup.run(n)
    elapsed = time.time() - t0
    tracker.join()  # every rank sent shutdown — job complete
    tracker.close()
    assert sup.relaunches == 2
    assert sorted(ranks_final) == list(range(n))
    for tid in die_once:
        assert ranks_final[tid] == ranks_first[tid]  # same rank reclaimed
    # the dead ranks had tree+ring neighbors; at least one survivor per
    # death must have gone through the self-heal path
    assert len(set(healed)) >= 2, healed
    assert elapsed < 90, f"drill took {elapsed:.1f}s"


def test_close_terminates_state_thread():
    """tracker.close() must stop the state thread even with the job
    incomplete (submit()'s abort path relies on it; the state thread
    waits on its event queue, not accept())."""
    tracker = RabitTracker("127.0.0.1", 2)
    tracker.start(2)
    assert tracker.alive()
    tracker.close()
    deadline = time.time() + 5
    while tracker.alive() and time.time() < deadline:
        time.sleep(0.05)
    assert not tracker.alive()


def test_inflight_rank_cannot_be_claimed():
    """A rank whose assignment session is still running is owned: a
    second client claiming it mid-brokering must be rejected, exactly as
    if the first had already completed (serial-tracker semantics)."""
    tracker = RabitTracker("127.0.0.1", 2, client_timeout=5.0)
    tracker.start(2)
    # honest client claims rank 0 and parks mid-brokering
    honest = _handshake(tracker.port, rank=0, world=2, jobid="jA")
    assert honest.recv_int() == 0
    time.sleep(0.3)
    # hijacker claims the in-flight rank: must be dropped (its connection
    # closes without a rank echo)
    hijack = _handshake(tracker.port, rank=0, jobid="jB")
    with pytest.raises((ConnectionError, OSError)):
        hijack.recv_int()
    hijack.close()
    honest.close()
    tracker.close()


def test_inflight_jobid_cannot_claim_second_rank():
    """The jobid→rank memo is recorded on session completion; a jobid
    with an assignment still in flight must not be able to broker a
    SECOND rank concurrently (serial-tracker memo semantics)."""
    tracker = RabitTracker("127.0.0.1", 4, client_timeout=5.0)
    tracker.start(4)
    honest = _handshake(tracker.port, rank=0, world=4, jobid="jA")
    assert honest.recv_int() == 0  # mid-brokering, memo not yet recorded
    time.sleep(0.3)
    dup = _handshake(tracker.port, rank=3, jobid="jA")
    with pytest.raises((ConnectionError, OSError)):
        dup.recv_int()
    dup.close()
    honest.close()
    tracker.close()


def test_tracker_rejects_rank_hijack():
    """A hostile client claiming a live worker's rank (with a different
    jobid) is rejected by the jobid→rank consistency check; the real job
    completes untouched."""
    tracker = RabitTracker("127.0.0.1", 2)
    tracker.start(2)
    w0 = RabitWorker("127.0.0.1", tracker.port, jobid="0")
    w1 = RabitWorker("127.0.0.1", tracker.port, jobid="1")
    t1 = threading.Thread(target=lambda: w1.start(world_size=-1))
    t1.start()
    r0 = w0.start(world_size=2)
    t1.join(timeout=15)

    # job is live; attacker claims rank r0 under a foreign jobid
    fs = _handshake(tracker.port, rank=r0, jobid="evil", cmd="start")
    fs.sock.settimeout(10)
    try:
        data = fs.sock.recv(4)
    except (ConnectionResetError, OSError):
        data = b""
    assert data == b""  # dropped, no rank frame sent
    fs.close()

    w0.shutdown()
    w1.shutdown()
    tracker.join()
    tracker.close()


# -- backends (command builders, no cluster needed) --------------------------

def parse(argv):
    return tracker_opts.get_opts(argv)


def test_opts_parsing_and_memory():
    args = parse(
        ["--cluster", "local", "--num-workers", "3",
         "--worker-memory", "2g", "echo", "hi"]
    )
    assert args.num_workers == 3
    assert args.worker_memory_mb == 2048
    assert args.command == ["echo", "hi"]
    with pytest.raises(RuntimeError, match="Invalid memory"):
        tracker_opts.get_memory_mb("2x")


def test_opts_cluster_env_fallback(monkeypatch):
    monkeypatch.setenv("DMLC_SUBMIT_CLUSTER", "ssh")
    args = parse(["--num-workers", "1", "true"])
    assert args.cluster == "ssh"


def test_every_cluster_dispatches():
    for cluster in tracker_opts.CLUSTERS:
        assert callable(get_backend(cluster))


def test_ssh_command_builder(tmp_path):
    hosts = tmp_path / "hosts"
    hosts.write_text("node1\nnode2:2222\n# comment\n")
    parsed = ssh_backend.read_hosts(str(hosts))
    assert parsed == [("node1", 22), ("node2", 2222)]
    cmd = ssh_backend.build_ssh_command(
        "node1", 22, ["./train", "data"], {"DMLC_NUM_WORKER": 2},
        "worker", 0, "/work",
    )
    joined = " ".join(cmd)
    assert "ssh" in cmd[0] and "node1" in cmd
    assert "DMLC_ROLE=worker" in joined and "DMLC_NODE_HOST=node1" in joined
    assert "cd /work; ./train data" in joined


def test_mpi_command_builder():
    cmd = mpi_backend.build_mpirun(
        4, "worker", ["./app"], {"DMLC_TRACKER_PORT": 9091}, "openmpi"
    )
    assert cmd[:3] == ["mpirun", "-n", "4"]
    assert "-x" in cmd and "DMLC_ROLE=worker" in " ".join(cmd)
    cmd2 = mpi_backend.build_mpirun(2, "server", ["./app"], {}, "mpich")
    assert "-env" in cmd2


def test_slurm_command_builder():
    cmd = slurm_backend.build_srun(4, 2, "worker", ["./app"], {"X": 1})
    assert cmd[0] == "srun" and "--nodes=2" in cmd and "--ntasks=4" in cmd
    assert any("DMLC_ROLE=worker" in c for c in cmd)


def test_kubernetes_manifests():
    args = parse(
        ["--cluster", "kubernetes", "--num-workers", "2",
         "--num-servers", "1", "--jobname", "tj", "./app"]
    )
    manifests = kube_backend.build_all_manifests(
        args, {"DMLC_TRACKER_URI": "10.0.0.1"}
    )
    assert len(manifests) == 3
    names = [m["metadata"]["name"] for m in manifests]
    assert names == ["tj-worker-0", "tj-worker-1", "tj-server-0"]
    env0 = {e["name"]: e["value"] for e in
            manifests[0]["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env0["DMLC_ROLE"] == "worker" and env0["DMLC_TASK_ID"] == "0"


def test_mesos_command_builder():
    cmd = mesos_backend.build_mesos_execute(
        "leader:5050", "job-0", ["./app"], {"A": "b"}, "worker", 0, 2, 1024
    )
    assert "--master=leader:5050" in cmd
    assert any("cpus:2;mem:1024" in c for c in cmd)


def test_tpu_pod_command_builder():
    remote = tpu_pod.build_worker_command(
        1, 4, ["python", "train.py"],
        {"DMLC_TRACKER_URI": "10.0.0.9", "DMLC_TRACKER_PORT": 9091},
        "10.0.0.9",
    )
    assert "JAX_COORDINATOR_ADDRESS=10.0.0.9:8476" in remote
    assert "JAX_PROCESS_ID=1" in remote and "JAX_NUM_PROCESSES=4" in remote
    assert "DMLC_ROLE=worker" in remote and remote.endswith("python train.py")
    cmd = tpu_pod.build_gcloud_ssh("mypod", "us-central2-b", "proj", 1, remote)
    assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "mypod"]
    assert "--worker" in cmd


def test_launcher_derive_role():
    assert derive_role({"DMLC_ROLE": "server"}) == "server"
    assert derive_role({"DMLC_TASK_ID": "0", "DMLC_NUM_WORKER": "2"}) == "worker"
    assert derive_role({"DMLC_TASK_ID": "3", "DMLC_NUM_WORKER": "2"}) == "server"
    assert derive_role({"SGE_TASK_ID": "4", "DMLC_NUM_WORKER": "2"}) == "server"


# -- end-to-end local submit -------------------------------------------------

WORKER_SNIPPET = """
import os, sys
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.tracker.client import RabitWorker
w = RabitWorker()
rank = w.start()
with open({out!r} + str(rank), "w") as f:
    f.write("%s %s %s" % (rank, os.environ["DMLC_ROLE"], os.environ["DMLC_TASK_ID"]))
w.shutdown()
"""


def test_local_submit_end_to_end(tmp_path):
    """dmlc-submit --cluster local -n 2 with real rabit workers."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = str(tmp_path / "rank")
    snippet = WORKER_SNIPPET.format(repo=repo, out=out)
    script = tmp_path / "worker.py"
    script.write_text(snippet)
    import importlib

    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main(
        ["--cluster", "local", "--num-workers", "2",
         "--host-ip", "127.0.0.1", sys.executable, str(script)]
    )
    got = set()
    for r in range(2):
        path = out + str(r)
        assert os.path.exists(path), f"missing {path}"
        rank, role, _task = open(path).read().split()
        got.add(int(rank))
        assert role == "worker"
    assert got == {0, 1}


def test_worker_link_wait_times_out_not_wedges(monkeypatch):
    """A worker told to await a peer link that never dials in must fail
    with a diagnosis after DMLC_LINK_WAIT_TIMEOUT, never block forever
    (the relaunched-worker wedge: survivors wired to a dead predecessor
    won't reconnect unless the app re-enters rendezvous)."""
    import socket as socket_mod
    import threading

    from dmlc_core_tpu.tracker.client import RabitWorker
    from dmlc_core_tpu.tracker.protocol import MAGIC, FramedSocket

    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def fake_tracker():
        conn, _ = srv.accept()
        fs = FramedSocket(conn)
        assert fs.recv_int() == MAGIC
        fs.send_int(MAGIC)
        fs.recv_int()  # rank
        fs.recv_int()  # world
        fs.recv_str()  # jobid
        # the cmd string may carry a piggybacked trace context
        from dmlc_core_tpu.tracker.protocol import unpack_cmd

        assert unpack_cmd(fs.recv_str())[0] == "start"
        fs.send_int(0)   # rank
        fs.send_int(-1)  # parent
        fs.send_int(2)   # world_size
        fs.send_int(0)   # n tree neighbors
        fs.send_int(-1)  # ring prev
        fs.send_int(-1)  # ring next
        fs.recv_int()    # goodset size (0)
        fs.send_int(0)   # n_conn: nothing to dial out
        fs.send_int(1)   # n_wait: one incoming link that never comes
        fs.recv_int()    # n_err
        fs.recv_int()    # my_port
        conn.close()

    t = threading.Thread(target=fake_tracker, daemon=True)
    t.start()
    monkeypatch.setenv("DMLC_LINK_WAIT_TIMEOUT", "0.3")
    w = RabitWorker(
        tracker_uri="127.0.0.1", tracker_port=srv.getsockname()[1]
    )
    with pytest.raises(RuntimeError, match="timed out after .* incoming"):
        w.start()
    srv.close()


def test_worker_link_wait_identify_stall_times_out(monkeypatch):
    """The deadline also covers a connector that never sends its rank
    (stray probe / half-dead peer): recv on the accepted socket must not
    block past the shared deadline."""
    import socket as socket_mod
    import threading

    from dmlc_core_tpu.tracker.client import RabitWorker
    from dmlc_core_tpu.tracker.protocol import MAGIC, FramedSocket

    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    worker_port = []

    def fake_tracker():
        conn, _ = srv.accept()
        fs = FramedSocket(conn)
        assert fs.recv_int() == MAGIC
        fs.send_int(MAGIC)
        fs.recv_int(); fs.recv_int(); fs.recv_str(); fs.recv_str()
        fs.send_int(0); fs.send_int(-1); fs.send_int(2)
        fs.send_int(0); fs.send_int(-1); fs.send_int(-1)
        fs.recv_int()
        fs.send_int(0)  # n_conn
        fs.send_int(1)  # n_wait
        fs.recv_int()   # n_err
        worker_port.append(fs.recv_int())
        # dial the worker's listener but never send the rank int
        mute = socket_mod.create_connection(("127.0.0.1", worker_port[0]))
        mute.recv(1)  # hold open until the worker gives up
        mute.close()
        conn.close()

    threading.Thread(target=fake_tracker, daemon=True).start()
    monkeypatch.setenv("DMLC_LINK_WAIT_TIMEOUT", "0.4")
    w = RabitWorker(
        tracker_uri="127.0.0.1", tracker_port=srv.getsockname()[1]
    )
    with pytest.raises(RuntimeError, match="timed out after"):
        w.start()
    srv.close()


def test_non_rabit_command_aborts_instead_of_wedging(monkeypatch):
    """A launched command that exits 0 without ever joining the
    rendezvous must fail fast with a diagnosis, not hang the join
    forever (the reference tracker wedges here, tracker.py:293-311)."""
    import importlib

    monkeypatch.setenv("DMLC_RENDEZVOUS_GRACE", "0.5")
    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    with pytest.raises(RuntimeError, match="rendezvous never completed"):
        submit_mod.main(
            ["--cluster", "local", "--num-workers", "2",
             "--host-ip", "127.0.0.1", "true"]
        )


def test_dry_run_does_not_block(capsys):
    """--dry-run prints launch commands and returns without a tracker."""
    import importlib

    submit_mod = importlib.import_module("dmlc_core_tpu.tracker.submit")
    submit_mod.main(
        ["--cluster", "tpu-pod", "--num-workers", "2", "--dry-run",
         "--host-ip", "127.0.0.1", "--tpu-name", "pod1", "python3", "t.py"]
    )
    out = capsys.readouterr().out
    assert out.count("[dry-run]") == 2
    assert "JAX_COORDINATOR_ADDRESS" in out
