"""The AST lint gate (tools/lint.py) — reference travis lint stage
(scripts/travis/travis_script.sh:19-23) rebuilt dependency-free.

Each check must (a) catch its violation class and (b) stay quiet on the
idioms this repo relies on (noqa re-exports, format specs, `import x as
x`), and the repo itself must lint clean — the gate `make check` runs.
"""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint  # noqa: E402


def findings(src, tmp_path, name="mod.py"):
    f = tmp_path / name
    f.write_text(src)
    return [(code, line) for (_, line, code, _) in lint.lint_file(f)]


def codes(src, tmp_path):
    return [c for c, _ in findings(src, tmp_path)]


def test_unused_import_flagged(tmp_path):
    assert codes("import os\n", tmp_path) == ["L001"]
    assert codes("from typing import Dict\nx: 'Dict' = {}\n", tmp_path) in (
        [],
        ["L001"],
    )  # string annotations parse as code on py3.12 AnnAssign → used


def test_used_import_quiet(tmp_path):
    assert codes("import os\nprint(os.sep)\n", tmp_path) == []
    # attribute-root usage counts
    assert codes("import os.path\nos.path.join('a')\n", tmp_path) == []


def test_reexport_idioms_quiet(tmp_path):
    assert codes("from .x import y as y\n", tmp_path) == []
    assert codes("import numpy as numpy\n", tmp_path) == []
    assert codes("from .x import y  # noqa: F401\n", tmp_path) == []
    # __all__ strings count as uses
    assert codes("from .x import y\n__all__ = ['y']\n", tmp_path) == []


def test_noqa_on_multiline_import_head(tmp_path):
    src = "from .x import (  # noqa: F401\n    a,\n    b,\n)\n"
    assert codes(src, tmp_path) == []


def test_bare_except_flagged(tmp_path):
    src = "try:\n    pass\nexcept:\n    pass\n"
    assert codes(src, tmp_path) == ["L002"]
    ok = "try:\n    pass\nexcept Exception:\n    pass\n"
    assert codes(ok, tmp_path) == []


def test_mutable_default_flagged(tmp_path):
    assert codes("def f(x=[]):\n    return x\n", tmp_path) == ["L003"]
    assert codes("def f(*, x={}):\n    return x\n", tmp_path) == ["L003"]
    assert codes("def f(x=()):\n    return x\n", tmp_path) == []


def test_fstring_without_placeholder_flagged(tmp_path):
    assert codes("x = f'plain'\n", tmp_path) == ["L004"]
    assert codes("x = f'{1}'\n", tmp_path) == []
    # a format spec is itself a JoinedStr — must NOT be flagged
    assert codes("x = f'{3.14:.2f}'\n", tmp_path) == []


def test_duplicate_dict_key_flagged(tmp_path):
    assert codes("d = {'a': 1, 'a': 2}\n", tmp_path) == ["L005"]
    assert codes("d = {'a': 1, 'b': 2}\n", tmp_path) == []


def test_direct_urlopen_flagged(tmp_path):
    src = "import urllib.request\nurllib.request.urlopen('http://x')\n"
    assert codes(src, tmp_path) == ["L006"]
    src = (
        "from urllib.request import urlopen\nurlopen('http://x')\n"
    )
    assert codes(src, tmp_path) == ["L006"]
    # an alias does not dodge the rule
    src = (
        "from urllib.request import urlopen as uo\nuo('http://x')\n"
    )
    assert codes(src, tmp_path) == ["L006"]


def test_urlopen_quiet_in_retry_layer(tmp_path):
    """io/retry.py owns the single urlopen call site and is exempt."""
    d = tmp_path / "io"
    d.mkdir()
    src = "import urllib.request\nurllib.request.urlopen('http://x')\n"
    f = d / "retry.py"
    f.write_text(src)
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []


def test_direct_device_put_flagged(tmp_path):
    src = "import jax\njax.device_put(x)\n"
    assert codes(src, tmp_path) == ["L007"]
    src = "from jax import device_put\ndevice_put(x)\n"
    assert codes(src, tmp_path) == ["L007"]
    # an alias does not dodge the rule
    src = "from jax import device_put as dp\ndp(x)\n"
    assert codes(src, tmp_path) == ["L007"]
    # any attribute call counts (jnp/numpy-style indirection)
    src = "import jax.numpy\njax.numpy.device_put(x)\n"
    assert codes(src, tmp_path) == ["L007"]


def test_device_put_sanctioned_wrapper_quiet(tmp_path):
    """The staging layer's wrapper imported as a bare name is the
    sanctioned escape hatch (spmd.py parameter placement)."""
    src = (
        "from dmlc_core_tpu.staging.pipeline import device_put\n"
        "device_put(x)\n"
    )
    assert codes(src, tmp_path) == []
    # per-line opt-out for raw link probes
    src = "import jax\njax.device_put(x)  # noqa: L007 (raw probe)\n"
    assert codes(src, tmp_path) == []


def test_device_put_quiet_in_staging_layer(tmp_path):
    """dmlc_core_tpu/staging/ owns the transfer call sites."""
    d = tmp_path / "dmlc_core_tpu" / "staging"
    d.mkdir(parents=True)
    f = d / "pipeline.py"
    f.write_text("import jax\njax.device_put(x)\n")
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []


def _lib_findings(src, tmp_path, name="mod.py"):
    """Findings for a file living under a dmlc_core_tpu/ tree (the L008
    scope — the rule must not fire outside the library)."""
    d = tmp_path / "dmlc_core_tpu" / "io"
    d.mkdir(parents=True, exist_ok=True)
    f = d / name
    f.write_text(src)
    return [(code, line) for (_, line, code, _) in lint.lint_file(f)]


def test_wall_clock_time_flagged_in_library(tmp_path):
    src = "import time\nt0 = time.time()\n"
    assert [c for c, _ in _lib_findings(src, tmp_path)] == ["L008"]
    # a bare `time()` bound by from-import does not dodge the rule
    src = "from time import time\nt0 = time()\n"
    assert [c for c, _ in _lib_findings(src, tmp_path)] == ["L008"]
    # ...nor does an alias
    src = "from time import time as now\nt0 = now()\n"
    assert [c for c, _ in _lib_findings(src, tmp_path)] == ["L008"]
    # ...nor does aliasing the MODULE (the repo's `import time as _time`
    # idiom must not become an escape hatch)
    src = "import time as _time\nt0 = _time.time()\n"
    assert [c for c, _ in _lib_findings(src, tmp_path)] == ["L008"]


def test_wall_clock_time_quiet_on_sanctioned_uses(tmp_path):
    # the sanctioned clocks are quiet
    src = (
        "import time\n"
        "t0 = time.perf_counter()\n"
        "t1 = time.monotonic()\n"
        "time.sleep(0.1)\n"
    )
    assert _lib_findings(src, tmp_path) == []
    # per-line opt-out for genuine wall-clock sites (token expiry, JWT)
    src = "import time\nexp = time.time()  # noqa: L008 (token expiry)\n"
    assert _lib_findings(src, tmp_path) == []
    # unrelated .time() attribute calls (datetime.time etc.) are not ours
    src = "import datetime\nd = datetime.datetime.now().time()\n"
    assert _lib_findings(src, tmp_path) == []


def test_wall_clock_time_unscoped_outside_library(tmp_path):
    """L008 is scoped to dmlc_core_tpu/: benches/tests/tools measuring
    with wall-clock on purpose are not the library's business."""
    assert codes("import time\nt0 = time.time()\n", tmp_path) == []


def test_codec_import_flagged(tmp_path):
    """L009: compression modules are one codec site (io/codec.py),
    mirroring the L006 (urlopen) and L008 (time.time) pattern."""
    assert codes("import zlib\nzlib.crc32(b'x')\n", tmp_path) == ["L009"]
    assert codes("import gzip\ngzip.compress(b'x')\n", tmp_path) == ["L009"]
    assert codes("import zstandard\nzstandard.ZstdCompressor()\n",
                 tmp_path) == ["L009"]
    # submodule and from-imports do not dodge the rule
    assert codes("import lz4.frame\nlz4.frame.compress(b'x')\n",
                 tmp_path) == ["L009"]
    assert codes("from zlib import crc32\ncrc32(b'x')\n",
                 tmp_path) == ["L009"]
    # ...nor does an alias
    assert codes("import zlib as z\nz.decompress(b'x')\n",
                 tmp_path) == ["L009"]


def test_codec_import_quiet_outside_violations(tmp_path):
    # unrelated modules whose names merely contain a codec name
    assert codes("import zlib_tools\nzlib_tools.go()\n", tmp_path) == []
    # the sanctioned route: everything compresses through the codec layer
    src = (
        "from dmlc_core_tpu.io.codec import get_codec\n"
        "get_codec('zlib')\n"
    )
    assert codes(src, tmp_path) == []
    # per-line opt-out works like every other rule
    assert codes("import zlib  # noqa: L009 (test fixture)\nzlib.crc32\n",
                 tmp_path) == []


def test_codec_import_quiet_in_codec_layer(tmp_path):
    """io/codec.py owns the compression imports and is exempt."""
    d = tmp_path / "io"
    d.mkdir()
    f = d / "codec.py"
    f.write_text("import zlib\nimport gzip\nzlib.crc32(gzip.compress(b''))\n")
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []


def test_shm_socket_import_flagged_in_io(tmp_path):
    """L010: raw sockets inside dmlc_core_tpu/io/ are one layer
    (io/blockcache.py + io/lookup.py), mirroring L006/L008/L009."""
    assert [c for c, _ in _lib_findings(
        "import socket\nsocket.socket()\n", tmp_path)] == ["L010"]
    assert [c for c, _ in _lib_findings(
        "from socket import socket\nsocket()\n", tmp_path)] == ["L010"]


def test_shm_socket_quiet_outside_io_and_in_blockcache(tmp_path):
    # the rule is scoped to dmlc_core_tpu/io/ — the tracker's sockets
    # (rendezvous protocol) are its own business
    assert codes("import socket\nsocket.socket()\n", tmp_path) == []
    d = tmp_path / "dmlc_core_tpu" / "tracker"
    d.mkdir(parents=True)
    f = d / "protocol.py"
    f.write_text("import socket\nsocket.socket()\n")
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # io/blockcache.py owns the control-plane socket and is exempt
    d = tmp_path / "dmlc_core_tpu" / "io"
    d.mkdir(parents=True)
    f = d / "blockcache.py"
    f.write_text("import socket\nsocket.socket()\n")
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # plain multiprocessing (pools, queues) is NOT the rule's business
    assert _lib_findings(
        "import multiprocessing\nmultiprocessing.cpu_count()\n", tmp_path
    ) == []
    # per-line opt-out (io/retry.py's exception classification)
    assert _lib_findings(
        "import socket  # noqa: L010 (exception classification)\n"
        "socket.timeout\n", tmp_path
    ) == []


def test_shm_segment_construction_flagged_library_wide(tmp_path):
    """L019: shm segment construction is one module (io/shm.py's
    ShmSegment) across the WHOLE library — imports of the primitives
    and alias-aware shm_open/shm_unlink/SharedMemory calls both flag."""
    assert [c for c, _ in _lib_findings(
        "import _posixshmem\n_posixshmem.shm_open\n", tmp_path)
    ] == ["L019"]
    assert [c for c, _ in _lib_findings(
        "import multiprocessing.shared_memory as sm\nsm.SharedMemory\n",
        tmp_path)] == ["L019"]
    assert [c for c, _ in _lib_findings(
        "from multiprocessing import shared_memory\n"
        "shared_memory.SharedMemory\n", tmp_path)] == ["L019"]
    assert [c for c, _ in _lib_findings(
        "from multiprocessing.shared_memory import SharedMemory\n"
        "SharedMemory\n", tmp_path)] == ["L019"]
    # a CALL through an alias flags the call site too — alias games
    # don't dodge the rule (the L014/L015 pattern)
    assert [c for c, _ in _lib_findings(
        "import _posixshmem as p\np.shm_open('/x', 0)\n", tmp_path)
    ] == ["L019", "L019"]
    assert [c for c, _ in _lib_findings(
        "from multiprocessing.shared_memory import SharedMemory as SM\n"
        "SM(name='x')\n", tmp_path)] == ["L019", "L019"]
    # the rule covers the whole library, not just io/ — a tracker
    # module minting segments forks the lifecycle policy all the same
    d = tmp_path / "dmlc_core_tpu" / "tracker"
    d.mkdir(parents=True)
    f = d / "ledger.py"
    f.write_text("import _posixshmem\n_posixshmem.shm_open('/x', 0)\n")
    assert [c for (_, _, c, _) in lint.lint_file(f)] == ["L019", "L019"]


def test_shm_segment_construction_quiet_in_shm_and_outside(tmp_path):
    # io/shm.py owns the construction site and is exempt
    d = tmp_path / "dmlc_core_tpu" / "io"
    d.mkdir(parents=True)
    f = d / "shm.py"
    f.write_text("import _posixshmem\n_posixshmem.shm_open('/x', 0)\n")
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # outside the library the rule does not fire (tests build probe
    # segments; scripts may use the stdlib wrapper)
    assert codes(
        "from multiprocessing import shared_memory\n"
        "shared_memory.SharedMemory(name='x')\n", tmp_path) == []
    # file-backed mmap is NOT this rule's business (io/split.py,
    # staging/fused.py map files, not segments)
    assert _lib_findings(
        "import mmap\nimport os\n"
        "m = mmap.mmap(os.open('/f', 0), 0)\n", tmp_path) == []
    # riding the sanctioned primitive is the blessed route
    assert _lib_findings(
        "from dmlc_core_tpu.io.shm import ShmSegment\n"
        "ShmSegment('x', create=True, size=8)\n", tmp_path) == []


def test_trace_event_literal_flagged_in_library(tmp_path):
    """L011: Chrome trace-event emission and the trace-file format are
    one site (telemetry/tracing.py), mirroring L006/L008-L010."""
    # an event-shaped dict literal ("ph" + "ts" keys)
    src = 'ev = {"ph": "X", "ts": 1.0, "name": "x"}\n'
    assert [c for c, _ in _lib_findings(src, tmp_path)] == ["L011"]
    # the file container shape
    src = 'out = {"traceEvents": [], "displayTimeUnit": "ms"}\n'
    assert [c for c, _ in _lib_findings(src, tmp_path)] == ["L011"]
    # per-line opt-out works like every other rule
    src = 'ev = {"ph": "X", "ts": 0}  # noqa: L011 (fixture)\n'
    assert _lib_findings(src, tmp_path) == []


def test_trace_event_literal_quiet_on_benign_shapes(tmp_path):
    # reading keys from a LOADED trace is not emission
    src = 'x = trace["traceEvents"]\ny = ev.get("ts")\n'
    assert _lib_findings(src, tmp_path) == []
    # "ph" or "ts" alone is not the event shape
    assert _lib_findings('d = {"ph": 7.2}\n', tmp_path) == []
    assert _lib_findings('d = {"ts": 1.0}\n', tmp_path) == []
    # scoped to dmlc_core_tpu/ — scripts outside the library may build
    # whatever dicts they like
    src = 'ev = {"ph": "X", "ts": 1.0}\n'
    assert codes(src, tmp_path) == []
    # the flight recorder itself owns the format and is exempt
    d = tmp_path / "dmlc_core_tpu" / "telemetry"
    d.mkdir(parents=True)
    f = d / "tracing.py"
    f.write_text('ev = {"ph": "X", "ts": 1.0}\n'
                 'out = {"traceEvents": [ev]}\n')
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []


def test_thread_pool_creation_flagged_in_io(tmp_path):
    """L012: thread-pool creation inside dmlc_core_tpu/io/ is confined
    to codec.py's decode pool and spanfetch.py's fetch pool — an ad-hoc
    executor bypasses the cgroup-aware sizing and the in-flight byte
    budget."""
    assert [c for c, _ in _lib_findings(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "p = ThreadPoolExecutor(4)\n", tmp_path)] == ["L012"]
    assert [c for c, _ in _lib_findings(
        "import concurrent.futures as cf\n"
        "p = cf.ThreadPoolExecutor(max_workers=2)\n", tmp_path)
    ] == ["L012"]
    assert [c for c, _ in _lib_findings(
        "from concurrent.futures import ThreadPoolExecutor as TPE\n"
        "p = TPE(2)\n", tmp_path)] == ["L012"]
    assert [c for c, _ in _lib_findings(
        "from multiprocessing.pool import ThreadPool\n"
        "p = ThreadPool(2)\n", tmp_path)] == ["L012"]
    # per-line opt-out works like every other rule
    assert _lib_findings(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "p = ThreadPoolExecutor(2)  # noqa: L012 (fixture)\n", tmp_path
    ) == []


def test_thread_pool_creation_quiet_outside_io_and_in_owners(tmp_path):
    # scoped to dmlc_core_tpu/io/ — staging/tracker pools are governed
    # by their own sizing policies, and scripts may do as they like
    assert codes(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "p = ThreadPoolExecutor(4)\n", tmp_path) == []
    d = tmp_path / "dmlc_core_tpu" / "staging"
    d.mkdir(parents=True)
    f = d / "pipeline.py"
    f.write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "p = ThreadPoolExecutor(4)\n"
    )
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # the two sanctioned owners are exempt
    d = tmp_path / "dmlc_core_tpu" / "io"
    d.mkdir(parents=True)
    for owner in ("codec.py", "spanfetch.py"):
        f = d / owner
        f.write_text(
            "from concurrent.futures import ThreadPoolExecutor\n"
            "p = ThreadPoolExecutor(4)\n"
        )
        assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # mere Future usage / pool REFERENCES are not creation
    assert _lib_findings(
        "from concurrent.futures import Future\nf = Future()\n", tmp_path
    ) == []


def _tracker_findings(src, tmp_path, name="mod.py"):
    """Findings for a file living under dmlc_core_tpu/tracker/ (the
    L013 scope)."""
    d = tmp_path / "dmlc_core_tpu" / "tracker"
    d.mkdir(parents=True, exist_ok=True)
    f = d / name
    f.write_text(src)
    return [(code, line) for (_, line, code, _) in lint.lint_file(f)]


def test_rendezvous_cmd_literal_flagged_in_tracker(tmp_path):
    """L013: the rendezvous command vocabulary is spelled out in
    tracker/protocol.py's CMD_* constants only — a literal elsewhere in
    tracker/ can typo into a silently-dropped unknown command."""
    assert [c for c, _ in _tracker_findings(
        'if cmd == "shutdown":\n    pass\n', tmp_path)] == ["L013"]
    assert [c for c, _ in _tracker_findings(
        'fs.send_str("shard_lease")\n', tmp_path)] == ["L013"]
    assert [c for c, _ in _tracker_findings(
        'x = cmd in ("start", "recover")\n', tmp_path)
    ] == ["L013", "L013"]
    # per-line opt-out works like every other rule
    assert _tracker_findings(
        'ok = cmd == "metrics"  # noqa: L013 (fixture)\n', tmp_path
    ) == []


def test_rendezvous_cmd_literal_quiet_outside_scope(tmp_path):
    # tests/benches craft raw frames deliberately — out of scope
    assert codes('fs.send_str("metrics")\n', tmp_path) == []
    # elsewhere in the library too (the strings are only special on the
    # rendezvous wire)
    assert _lib_findings('mode = "print"\n', tmp_path) == []
    # protocol.py owns the constants and is exempt
    d = tmp_path / "dmlc_core_tpu" / "tracker"
    d.mkdir(parents=True)
    f = d / "protocol.py"
    f.write_text('CMD_METRICS = "metrics"\nCMD_START = "start"\n')
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # non-command strings in tracker/ are not the rule's business
    assert _tracker_findings('msg = "start listen on %s"\n', tmp_path) == []


def test_rendezvous_cmd_set_matches_protocol():
    """The lint's hardcoded vocabulary must track protocol.py's — a new
    command added there without updating L013 would reopen the literal
    loophole for exactly that command."""
    sys.path.insert(0, str(REPO))
    try:
        from dmlc_core_tpu.tracker import protocol
    finally:
        sys.path.pop(0)
    assert lint._L013_CMDS == protocol.RENDEZVOUS_CMDS


def test_socket_construction_flagged_in_tracker(tmp_path):
    """L014: raw socket construction inside dmlc_core_tpu/tracker/ is
    confined to protocol.py (listeners + dials) and collective.py (the
    peer-link data plane) — an ad-hoc socket forks connect/IO-timeout
    policy per call site."""
    assert [c for c, _ in _tracker_findings(
        "import socket\ns = socket.socket()\n", tmp_path)] == ["L014"]
    assert [c for c, _ in _tracker_findings(
        "import socket\n"
        "s = socket.create_connection(('h', 1), timeout=30)\n", tmp_path)
    ] == ["L014"]
    assert [c for c, _ in _tracker_findings(
        "import socket as sk\ns = sk.socket(sk.AF_INET)\n", tmp_path)
    ] == ["L014"]
    assert [c for c, _ in _tracker_findings(
        "from socket import socket as mksock\ns = mksock()\n", tmp_path)
    ] == ["L014"]
    assert [c for c, _ in _tracker_findings(
        "from socket import create_connection\n"
        "s = create_connection(('h', 1))\n", tmp_path)] == ["L014"]
    # per-line opt-out works like every other rule (the UDP route probe)
    assert _tracker_findings(
        "import socket\n"
        "s = socket.socket()  # noqa: L014 (fixture)\n", tmp_path
    ) == []


def test_socket_construction_quiet_outside_scope_and_in_owners(tmp_path):
    # tests/benches build raw sockets deliberately — out of scope
    assert codes("import socket\ns = socket.socket()\n", tmp_path) == []
    # elsewhere in the library too (io/ has its own L010 governing this)
    assert _lib_findings(
        "import socket  # noqa: L010\n"
        "s = socket.socket()\n", tmp_path) == []
    # the two sanctioned wire modules are exempt
    d = tmp_path / "dmlc_core_tpu" / "tracker"
    d.mkdir(parents=True, exist_ok=True)
    for owner in ("protocol.py", "collective.py"):
        f = d / owner
        f.write_text("import socket\ns = socket.socket()\n")
        assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # socket-module REFERENCES (constants, type annotations) are not
    # construction
    assert _tracker_findings(
        "import socket\nx = socket.SHUT_RDWR\n"
        "def f(s: socket.socket) -> None:\n    s.close()\n", tmp_path
    ) == []
    # an unrelated object's .socket attribute is not the socket module
    assert _tracker_findings(
        "s = server.socket.accept()\n", tmp_path) == []


def _dsserve_findings(src, tmp_path, name="mod.py"):
    """Findings for a file living under dmlc_core_tpu/dsserve/ (the
    L015 scope)."""
    d = tmp_path / "dmlc_core_tpu" / "dsserve"
    d.mkdir(parents=True, exist_ok=True)
    f = d / name
    f.write_text(src)
    return [(code, line) for (_, line, code, _) in lint.lint_file(f)]


def test_struct_framing_flagged_in_wire_scopes(tmp_path):
    """L015: the dsserve slot-frame header (and every other binary wire
    header in dsserve/ and tracker/) is packed/unpacked in exactly one
    module per protocol — a second struct site can drift field order or
    endianness and corrupt every frame after it."""
    assert [c for c, _ in _dsserve_findings(
        "import struct\nhdr = struct.pack('<IBq', 1, 2, 3)\n", tmp_path)
    ] == ["L015"]
    assert [c for c, _ in _dsserve_findings(
        "import struct\nf = struct.unpack('<I', b'xxxx')\n", tmp_path)
    ] == ["L015"]
    assert [c for c, _ in _dsserve_findings(
        "import struct as st\nh = st.Struct('<IBq')\n", tmp_path)
    ] == ["L015"]
    assert [c for c, _ in _dsserve_findings(
        "from struct import pack as p\nh = p('<I', 1)\n", tmp_path)
    ] == ["L015"]
    # tracker/ is scoped too (its frames belong to protocol.py /
    # collective.py)
    assert [c for c, _ in _tracker_findings(
        "import struct\nhdr = struct.pack('<i', 1)\n", tmp_path)
    ] == ["L015"]
    # per-line opt-out works like every other rule
    assert _dsserve_findings(
        "import struct\n"
        "h = struct.pack('<I', 1)  # noqa: L015 (fixture)\n", tmp_path
    ) == []


def test_struct_framing_quiet_outside_scope_and_in_owners(tmp_path):
    # recordio/codec/serializer frames live outside the scope — theirs
    # are FILE formats, not wire protocols, and they own their headers
    assert _lib_findings(
        "import struct\nh = struct.pack('<II', 1, 2)\n", tmp_path) == []
    # tests craft raw frames deliberately — out of scope
    assert codes(
        "import struct\nh = struct.pack('<I', 1)\n", tmp_path) == []
    # the sanctioned wire modules are exempt
    d = tmp_path / "dmlc_core_tpu" / "dsserve"
    d.mkdir(parents=True, exist_ok=True)
    f = d / "wire.py"
    f.write_text("import struct\nh = struct.Struct('<IBq')\n")
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    dt = tmp_path / "dmlc_core_tpu" / "tracker"
    dt.mkdir(parents=True, exist_ok=True)
    for owner in ("protocol.py", "collective.py"):
        f = dt / owner
        f.write_text("import struct\nh = struct.pack('<i', 1)\n")
        assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # struct-module references that are not pack/unpack calls are fine
    assert _dsserve_findings(
        "import struct\nn = struct.calcsize('<I')\n", tmp_path) == []


def test_socket_serving_loop_flagged_in_io(tmp_path):
    """L016: socket-serving request loops inside dmlc_core_tpu/io/ are
    confined to blockcache.py (shared-cache control plane) and
    lookup.py (point-read serve daemon) — a third loop forks connection
    lifecycle and frame hygiene per site."""
    # accept/listen on any object are the loop markers (no socket
    # import needed, so L010 stays out of the assertion)
    assert [c for c, _ in _lib_findings(
        "conn, addr = srv.accept()\n", tmp_path)] == ["L016"]
    assert [c for c, _ in _lib_findings(
        "srv.listen(64)\n", tmp_path)] == ["L016"]
    # socket.create_server under an import trips BOTH the import rule
    # (L010) and the serving rule
    assert sorted(c for c, _ in _lib_findings(
        "import socket\nsrv = socket.create_server(('', 0))\n", tmp_path
    )) == ["L010", "L016"]
    assert sorted(c for c, _ in _lib_findings(
        "from socket import create_server as cs\nsrv = cs(('', 0))\n",
        tmp_path,
    )) == ["L010", "L016"]
    # per-line opt-out works like every other rule
    assert _lib_findings(
        "srv.listen(4)  # noqa: L016 (fixture)\n", tmp_path) == []


def test_socket_serving_loop_quiet_outside_io_and_in_owners(tmp_path):
    # scoped to dmlc_core_tpu/io/ — the tracker and dsserve servers are
    # their own sanctioned wire layers, scripts do as they like
    assert codes("conn = srv.accept()\n", tmp_path) == []
    d = tmp_path / "dmlc_core_tpu" / "dsserve"
    d.mkdir(parents=True, exist_ok=True)
    f = d / "server.py"
    f.write_text("conn, a = srv.accept()\nsrv.listen(8)\n")
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # the two sanctioned io/ servers are exempt
    dio = tmp_path / "dmlc_core_tpu" / "io"
    dio.mkdir(parents=True, exist_ok=True)
    for owner in ("blockcache.py", "lookup.py"):
        f = dio / owner
        f.write_text(
            "import socket\nsrv = socket.create_server(('', 0))\n"
            "srv.listen(8)\nconn, a = srv.accept()\n"
        )
        assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # dialing out is not serving (that is L010's business when imported)
    assert _lib_findings("s = cs.connect(('h', 1))\n", tmp_path) == []


def test_syntax_error_reported_not_raised(tmp_path):
    assert codes("def f(:\n", tmp_path) == ["L000"]


def test_repo_lints_clean():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py")],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def _telemetry_findings(src, tmp_path, name="mod.py"):
    """Findings for a file under dmlc_core_tpu/telemetry/ (inside the
    L017 scope but away from L013/L014/L015's tracker-specific rules,
    so assertions isolate the trace-context codec rule)."""
    d = tmp_path / "dmlc_core_tpu" / "telemetry"
    d.mkdir(parents=True, exist_ok=True)
    f = d / name
    f.write_text(src)
    return [(code, line) for (_, line, code, _) in lint.lint_file(f)]


def test_trace_context_codec_flagged_in_wire_trees(tmp_path):
    """L017: the trace-context wire format (16-hex-digit ids, base-16
    parsing) is encoded/decoded only in telemetry/tracing.py — a
    hand-rolled copy elsewhere can drift the format and silently break
    every flow arrow."""
    hexfmt = "016" + "x"
    # f-string encode
    assert [c for c, _ in _telemetry_findings(
        f'ctx = f"{{tid:{hexfmt}}}-{{sid:{hexfmt}}}"\n', tmp_path)
    ] == ["L017", "L017"]
    # %-format and str.format literals carry the same marker
    assert [c for c, _ in _telemetry_findings(
        f'ctx = "%{hexfmt}" % tid\n', tmp_path)] == ["L017"]
    assert [c for c, _ in _telemetry_findings(
        f'ctx = format(tid, "{hexfmt}")\n', tmp_path)] == ["L017"]
    # base-16 decode, positionally or by keyword
    assert [c for c, _ in _telemetry_findings(
        'tid = int(ctx[:16], 16)\n', tmp_path)] == ["L017"]
    assert [c for c, _ in _telemetry_findings(
        'tid = int(ctx, base=16)\n', tmp_path)] == ["L017"]
    # the rule covers every wire-speaking tree (tracker/ shown here)
    assert "L017" in [c for c, _ in _tracker_findings(
        'tid = int(ctx, 16)\n', tmp_path)]
    # per-line opt-out works like every other rule
    assert _telemetry_findings(
        'tid = int(ctx, 16)  # noqa: L017 (fixture)\n', tmp_path) == []


def test_trace_context_codec_quiet_in_owner_and_outside_scope(tmp_path):
    # the flight recorder owns the codec
    d = tmp_path / "dmlc_core_tpu" / "telemetry"
    d.mkdir(parents=True, exist_ok=True)
    f = d / "tracing.py"
    f.write_text('ctx = int("ff", 16)\n')
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # data/ parses hex for its own reasons (csv \x escapes) — out of
    # scope; tests/benches too
    dd = tmp_path / "dmlc_core_tpu" / "data"
    dd.mkdir(parents=True, exist_ok=True)
    f2 = dd / "mod.py"
    f2.write_text('v = int(digits, 16)\n')
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f2)] == []
    assert codes('x = int("ff", 16)\n', tmp_path) == []
    # int() without a base-16 literal is not a decode
    assert _telemetry_findings('n = int(x)\nm = int(y, 10)\n',
                               tmp_path) == []


def test_trace_context_codec_gate_matches_repo_state():
    """The real tree passes L017 (the codec lives only in tracing.py):
    run the shipped check over the repo's own wire trees."""
    repo = lint.REPO
    findings = []
    for rel in ("dmlc_core_tpu/telemetry", "dmlc_core_tpu/tracker",
                "dmlc_core_tpu/dsserve", "dmlc_core_tpu/io",
                "dmlc_core_tpu/tools"):
        for f in sorted((repo / rel).rglob("*.py")):
            if "__pycache__" in f.parts:
                continue
            findings += [
                x for x in lint.lint_file(f) if x[2] == "L017"
            ]
    assert findings == []


def test_journal_crc_framing_flagged_in_tracker(tmp_path):
    # a second CRC-framing site in the tracker tree splits the
    # journal's wire format ownership
    assert [c for c, _ in _tracker_findings(
        "import binascii\nc = binascii.crc32(b'x')\n",
        tmp_path)] == ["L018"]
    assert [c for c, _ in _tracker_findings(
        "import zlib\nc = zlib.crc32(payload)\n",
        tmp_path) if c == "L018"] == ["L018"]
    # alias-aware: module aliases and from-import aliases both count
    assert [c for c, _ in _tracker_findings(
        "import binascii as ba\nc = ba.crc32(b'x')\n",
        tmp_path)] == ["L018"]
    assert [c for c, _ in _tracker_findings(
        "from binascii import crc32\nc = crc32(b'x')\n",
        tmp_path)] == ["L018"]
    assert [c for c, _ in _tracker_findings(
        "from zlib import crc32 as c32\nc = c32(b'x')\n",
        tmp_path) if c == "L018"] == ["L018"]
    # per-line opt-out works like every other rule
    assert [c for c, _ in _tracker_findings(
        "import binascii\n"
        "c = binascii.crc32(b'x')  # noqa: L018 (fixture)\n",
        tmp_path) if c == "L018"] == []


def test_journal_crc_framing_quiet_in_owner_and_outside_scope(tmp_path):
    # journal.py owns the framing — crc32 AND struct framing are both
    # allowed there (L018 owner exemption + the L015 exemption)
    d = tmp_path / "dmlc_core_tpu" / "tracker"
    d.mkdir(parents=True, exist_ok=True)
    f = d / "journal.py"
    f.write_text(
        "import binascii\nimport struct\n"
        "_HDR = struct.Struct('<II')\n"
        "crc = binascii.crc32(b'payload')\n")
    assert [(c, ln) for (_, ln, c, _) in lint.lint_file(f)] == []
    # outside the tracker tree nobody cares about crc32
    assert codes("import binascii\nc = binascii.crc32(b'x')\n",
                 tmp_path) == []
    assert [c for c, _ in _lib_findings(
        "import zlib\nc = zlib.crc32(b'x')\n", tmp_path)
            if c == "L018"] == []
    # an import alone, or an unrelated attribute, is not a finding
    assert [c for c, _ in _tracker_findings(
        "import binascii\nh = binascii.hexlify(b'x')\n",
        tmp_path) if c == "L018"] == []


def test_journal_crc_framing_gate_matches_repo_state():
    """The real tree passes L018 (CRC framing lives only in
    tracker/journal.py): run the shipped check over the tracker tree."""
    repo = lint.REPO
    findings = []
    for f in sorted((repo / "dmlc_core_tpu" / "tracker").rglob("*.py")):
        if "__pycache__" in f.parts:
            continue
        findings += [x for x in lint.lint_file(f) if x[2] == "L018"]
    assert findings == []


def test_stream_manifest_literal_flagged_library_wide(tmp_path):
    """L020: the "manifest.json" filename is spelled once — a literal
    anywhere else in the library (plain or f-string) hand-rolls the
    commit-point path."""
    assert [c for c, _ in _lib_findings(
        "p = dir_uri + '/manifest.json'\n", tmp_path)] == ["L020"]
    assert [c for c, _ in _lib_findings(
        "p = f'{d}/manifest.json'\n", tmp_path)] == ["L020"]
    assert [c for c, _ in _lib_findings(
        "import os\np = os.path.join(d, 'manifest.json')\n", tmp_path)
    ] == ["L020"]
    # the sanctioned alias — the imported constant — never flags
    assert [c for c, _ in _lib_findings(
        "from ..stream.manifest import MANIFEST_NAME\n"
        "p = d + '/' + MANIFEST_NAME\n", tmp_path) if c == "L020"] == []
    # per-line opt-out works like every other rule
    assert [c for c, _ in _lib_findings(
        "p = d + '/manifest.json'  # noqa: L020 (fixture)\n", tmp_path)
            if c == "L020"] == []


def test_stream_tail_frame_walk_flagged(tmp_path):
    """L020: decode_length-driven frame walks (where the committed
    prefix ends) are manifest.py's business — the import flags, and a
    call through a module alias flags the call site too."""
    assert [c for c, _ in _lib_findings(
        "from ..io.recordio import decode_length\n", tmp_path)
            if c == "L020"] == ["L020"]
    # aliasing the name doesn't dodge the rule; the call flags as well
    assert [c for c, _ in _lib_findings(
        "from dmlc_core_tpu.io.recordio import decode_length as dl\n"
        "n = dl(lrec)\n", tmp_path) if c == "L020"] == ["L020", "L020"]
    assert [c for c, _ in _lib_findings(
        "from ..io import recordio as rio\n"
        "n = rio.decode_length(lrec)\n", tmp_path) if c == "L020"
    ] == ["L020"]
    # the FLAG sniff (staging/fused.py's compression probe) is fine —
    # it never advances a walk, so it can't disagree about the tail
    assert [c for c, _ in _lib_findings(
        "from ..io.recordio import KMAGIC, decode_flag\n"
        "ok = decode_flag(lrec) & 4\n", tmp_path) if c == "L020"] == []


def test_stream_manifest_quiet_in_owner_and_outside_scope(tmp_path):
    # stream/manifest.py owns the filename AND the walks — both are
    # allowed there
    d = tmp_path / "dmlc_core_tpu" / "stream"
    d.mkdir(parents=True)
    f = d / "manifest.py"
    f.write_text(
        "from ..io.recordio import KMAGIC, decode_flag, decode_length\n"
        "MANIFEST_NAME = 'manifest.json'\n"
        "ok = magic == KMAGIC and decode_flag(lrec) < 4\n"
        "n = decode_length(lrec)\n")
    assert [c for (_, _, c, _) in lint.lint_file(f)] == []
    # docstrings that MENTION the filename are prose, not a spelling
    assert [c for c, _ in _lib_findings(
        '"""Reads the manifest.json commit point."""\n'
        "def f():\n"
        "    '''follows manifest.json'''\n", tmp_path) if c == "L020"] == []
    # outside dmlc_core_tpu/ (tests, tools) the rule does not apply
    assert codes("p = d + '/manifest.json'\n", tmp_path) == []


def test_stream_manifest_gate_matches_repo_state():
    """The real tree passes L020 (the filename and the tail-frame
    walks live only in stream/manifest.py)."""
    repo = lint.REPO
    findings = []
    for f in sorted((repo / "dmlc_core_tpu").rglob("*.py")):
        if "__pycache__" in f.parts:
            continue
        findings += [x for x in lint.lint_file(f) if x[2] == "L020"]
    assert findings == []
