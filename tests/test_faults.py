"""The robustness subsystem: RetryPolicy semantics, the fault://
injection filesystem, and the chaos round-trip acceptance — a golden
RecordIO dataset read through seeded resets + 5xx + short reads must be
byte-identical to the clean read, on both the sequential and the
windowed-shuffle paths, with the healed retries visible in io_stats().
"""

import random

import numpy as np
import pytest

from dmlc_core_tpu.io import retry
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.faults import FaultSpec, wrap_uri
from dmlc_core_tpu.io.filesystem import FileSystem, MemoryFileSystem
from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
from dmlc_core_tpu.io.retry import (
    HttpError,
    RetryingReadStream,
    RetryPolicy,
    is_transient,
)
from dmlc_core_tpu.io.stream import FileStream, MemoryStream, Stream
from dmlc_core_tpu.utils.logging import Error


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    """Policies read env at construction: run every retry at test speed."""
    monkeypatch.setenv("DMLC_RETRY_BASE_SECS", "0.001")
    monkeypatch.setenv("DMLC_RETRY_CAP_SECS", "0.01")


# -- classifier ---------------------------------------------------------------


def test_transient_classifier():
    import http.client
    import urllib.error

    assert is_transient(HttpError("m", status=500))
    assert is_transient(HttpError("m", status=503))
    assert is_transient(HttpError("m", status=429))
    assert is_transient(HttpError("m", status=408))
    assert not is_transient(HttpError("m", status=404))
    assert not is_transient(HttpError("m", status=403))
    assert is_transient(urllib.error.URLError(ConnectionResetError()))
    assert is_transient(urllib.error.URLError(TimeoutError()))
    assert not is_transient(urllib.error.URLError("bad url"))
    assert is_transient(http.client.IncompleteRead(b"xx"))
    assert is_transient(ConnectionResetError())
    assert is_transient(BrokenPipeError())
    assert is_transient(TimeoutError())
    assert not is_transient(ValueError("nope"))
    assert not is_transient(KeyError("nope"))


# -- RetryPolicy --------------------------------------------------------------


def test_policy_retries_then_succeeds():
    sleeps = []
    p = RetryPolicy(
        max_attempts=4, base_secs=0.01, cap_secs=0.05, budget_secs=10,
        sleep=sleeps.append, rng=random.Random(7),
    )
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("boom")
        return "ok"

    assert p.run(flaky) == "ok"
    assert p.retries == 2 and len(sleeps) == 2
    # decorrelated jitter stays within [base, cap]
    assert all(0.01 <= s <= 0.05 for s in sleeps)


def test_policy_exhaustion_reraises_last_error():
    p = RetryPolicy(
        max_attempts=3, base_secs=0.001, budget_secs=10, sleep=lambda d: None
    )
    boom = ConnectionResetError("the last one")
    with pytest.raises(ConnectionResetError, match="the last one"):
        p.run(lambda: (_ for _ in ()).throw(boom))
    assert p.retries == 2  # attempts-1 retries, then re-raise


def test_policy_nontransient_raises_immediately():
    p = RetryPolicy(max_attempts=5, sleep=lambda d: None)
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        p.run(bad)
    assert len(calls) == 1 and p.retries == 0


def test_policy_budget_bounds_total_backoff():
    """The per-stream cumulative budget caps the SUM of sleeps across
    operations; the would-be over-budget retry re-raises the cause."""
    sleeps = []
    p = RetryPolicy(
        max_attempts=100, base_secs=0.04, cap_secs=0.05, budget_secs=0.1,
        sleep=sleeps.append, rng=random.Random(3),
    )
    with pytest.raises(ConnectionResetError):
        p.run(lambda: (_ for _ in ()).throw(ConnectionResetError("x")))
    assert sum(sleeps) <= 0.1
    assert p.backoff_secs <= 0.1


def test_policy_counters_feed_global_stats():
    before = retry.stats()
    p = RetryPolicy(max_attempts=2, base_secs=0.001, sleep=lambda d: None)
    with pytest.raises(ConnectionResetError):
        p.run(lambda: (_ for _ in ()).throw(ConnectionResetError()))
    d = retry.stats_delta(before)
    assert d["retries"] == 1 and d["backoff_secs"] > 0


# -- RetryingReadStream -------------------------------------------------------


class _ExplodingStream(MemoryStream):
    """Seekable stream raising scripted exceptions at given GLOBAL read
    ordinals (the counter is shared across reopens, like a schedule)."""

    def __init__(self, data, explode_at, counter):
        super().__init__(data)
        self.explode_at = explode_at
        self.counter = counter

    def read(self, n=-1):
        self.counter[0] += 1
        if self.counter[0] in self.explode_at:
            raise ConnectionResetError("mid-read reset")
        return super().read(min(n, 10) if n > 0 else 10)


def test_retrying_read_stream_resumes_at_offset():
    data = bytes(range(200))
    streams = []
    counter = [0]

    def open_fn():
        s = _ExplodingStream(data, explode_at={3, 7}, counter=counter)
        streams.append(s)
        return s

    r = RetryingReadStream(open_fn, policy=RetryPolicy(sleep=lambda d: None))
    out = r.read(-1)
    assert out == data, "healed read must be byte-identical"
    assert len(streams) == 3  # two resets -> two reopens
    r.close()


def test_retrying_read_stream_open_failures_then_success():
    attempts = []

    def open_fn():
        attempts.append(1)
        if len(attempts) < 3:
            raise HttpError("GET x -> HTTP 503: busy", status=503)
        return MemoryStream(b"hello")

    r = RetryingReadStream(
        open_fn,
        policy=RetryPolicy(max_attempts=4, sleep=lambda d: None),
    )
    assert r.read(-1) == b"hello"


# -- fault:// unit behavior ---------------------------------------------------


def test_fault_spec_rejects_unknown_options():
    with pytest.raises(Error, match="unknown fault"):
        FaultSpec({"tyop": "1"})
    with pytest.raises(Error, match="not an integer"):
        FaultSpec({"resets": "many"})


def test_wrap_uri_forms():
    assert wrap_uri("/d/x.rec", "resets=2,seed=7") == (
        "fault://resets=2,seed=7/d/x.rec"
    )
    assert wrap_uri("file:///d/x.rec", "resets=1") == (
        "fault://resets=1/d/x.rec"
    )
    assert wrap_uri("/d/x.rec", "") == "/d/x.rec"
    with pytest.raises(Error, match="only wraps local paths"):
        wrap_uri("s3://b/k", "resets=1")


def test_fault_passthrough_and_stat_list(tmp_path):
    p = tmp_path / "plain.bin"
    p.write_bytes(b"abcdef" * 100)
    uri = f"fault://seed=1{p}"
    fs = FileSystem.get_instance(uri)
    info = fs.get_path_info(uri)
    assert info.size == 600 and info.type == "file"
    listing = fs.list_directory(f"fault://seed=1{tmp_path}")
    assert any(f.path == uri for f in listing)
    s = fs.open(uri, "r")
    assert s.read(-1) == b"abcdef" * 100
    s.close()


def test_fault_open_errors_then_success(tmp_path):
    p = tmp_path / "o.bin"
    p.write_bytes(b"payload")
    before = retry.stats()
    s = Stream.create(f"fault:///{str(p).lstrip('/')}?errors=2&seed=3", "r")
    assert s.read(-1) == b"payload"
    s.close()
    d = retry.stats_delta(before)
    assert d["faults_injected"] == 2 and d["retries"] == 2


def test_fault_exhausts_policy_past_attempt_cap(tmp_path, monkeypatch):
    monkeypatch.setenv("DMLC_RETRY_ATTEMPTS", "2")
    p = tmp_path / "o2.bin"
    p.write_bytes(b"payload")
    with pytest.raises(HttpError, match="HTTP 503"):
        Stream.create(f"fault://errors=5,seed=3{p}", "r").read(1)


def test_fault_truncated_write_raises(tmp_path):
    p = tmp_path / "w.bin"
    w = Stream.create(f"fault://wresets=1,seed=5{p}", "w")
    w.write(b"A" * 100)
    with pytest.raises(ConnectionResetError):
        for _ in range(50):
            w.write(b"B" * 100)
    # the truncation landed a partial object — exactly the crash shape
    # _write_atomic's verify-then-commit must keep away from final keys
    assert 0 < len(p.read_bytes()) < 5100


def test_fault_mem_inner_roundtrip():
    MemoryFileSystem._store["mem://bkt/obj"] = b"mem-bytes"
    try:
        s = Stream.create("fault://inner=mem,seed=2/bkt/obj", "r")
        assert s.read(-1) == b"mem-bytes"
        s.close()
    finally:
        MemoryFileSystem.reset()


# -- checkpoint crash consistency over fault:// -------------------------------


def test_write_atomic_crash_never_exposes_final_key(tmp_path):
    """A truncated write mid-save must leave the FINAL uri absent (only
    .tmp debris) — the crash-consistency contract of _write_atomic's
    remote path."""
    from dmlc_core_tpu.checkpoint import _write_atomic, load_pytree

    base = f"fault://wresets=1,seed=11{tmp_path}/ck.bin"
    tree = {"w": np.zeros(4096, dtype=np.float64)}  # big enough to split
    with pytest.raises(ConnectionResetError):
        _write_atomic(base, tree)
    assert not (tmp_path / "ck.bin").exists()
    # clean save through the same (now fault-free) path commits
    ok = f"fault://seed=11{tmp_path}/ck.bin"
    _write_atomic(ok, {"w": np.arange(8)})
    out = load_pytree(str(tmp_path / "ck.bin"))
    np.testing.assert_array_equal(out["w"], np.arange(8))
    assert not (tmp_path / "ck.bin.tmp").exists(), "tmp debris after commit"


# -- chaos round-trip acceptance ----------------------------------------------


@pytest.fixture
def golden_rec(tmp_path):
    """Golden rowrec-agnostic RecordIO dataset + count index."""
    rng = np.random.default_rng(3)
    recs = [
        rng.integers(0, 255, int(rng.integers(20, 200)), dtype=np.uint8)
        .tobytes()
        for _ in range(400)
    ]
    path = str(tmp_path / "golden.rec")
    idx = path + ".idx"
    with FileStream(path, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(f, fi)
        for i, r in enumerate(recs):
            w.write_record(r, i)
    return path, idx, recs


CHAOS = "resets=3,short=4,errors=2,seed=7"


def test_chaos_sequential_read_byte_identical(golden_rec):
    path, _idx, recs = golden_rec
    s = io_split.create(path, type="recordio", threaded=False)
    clean = [bytes(r) for r in s]
    s.close()
    assert clean == recs

    before = retry.stats()
    s = io_split.create(wrap_uri(path, CHAOS), type="recordio", threaded=False)
    chaos = [bytes(r) for r in s]
    stats = s.io_stats()
    s.close()
    assert chaos == recs, "chaos read diverged from the clean read"
    assert stats["retries"] > 0
    assert stats["faults_injected"] > 0
    assert stats["backoff_secs"] > 0
    assert retry.stats_delta(before)["retries"] == stats["retries"]


def test_chaos_windowed_shuffle_byte_identical(golden_rec):
    """The same seeded permutation must come back record-for-record
    identical through injected resets/5xx/short reads — order included
    (the windowed path re-reads coalesced spans via seek+read, so a
    mis-resumed offset would scramble records, not just corrupt one)."""
    path, idx, _recs = golden_rec
    sugar = f"?index={idx}&shuffle=window&window=64&merge_gap=4096&seed=5"
    s = io_split.create(path + sugar, type="recordio", threaded=False)
    clean = [bytes(r) for r in s]
    s.close()
    assert len(clean) == 400

    s = io_split.create(
        wrap_uri(path, CHAOS) + sugar, type="recordio", threaded=False
    )
    chaos = [bytes(r) for r in s]
    stats = s.io_stats()
    s.close()
    assert chaos == clean, "chaos windowed read diverged (rows or order)"
    assert stats["mode"] == "window"
    assert stats["retries"] > 0
    assert stats["faults_injected"] > 0


@pytest.mark.parametrize("codec", (None, "zlib"))
@pytest.mark.parametrize("mode", ("record", "batch", "window"))
def test_chaos_parallel_fetch_byte_identical(mode, codec, tmp_path):
    """ISSUE 9 acceptance: the CONCURRENT span fetcher under fault://
    mid-read resets + latency spikes + short reads heals to the exact
    clean serial-path order and bytes, across all three shuffle modes
    on v1 AND zlib containers, with retries > 0 — parallelism must
    change when bytes arrive, never what they are."""
    from tests.test_split_gather import (
        drain_records,
        make_indexed_rec,
        records_of,
    )

    records = records_of(110, tag="pf")
    p, idx = make_indexed_rec(str(tmp_path), records, codec=codec)
    sugar = dict(
        shuffle=mode, seed=8, window=24, merge_gap=0, batch_size=8
    )
    clean = io_split.IndexedRecordIOSplitter(p, idx, 0, 1, **sugar)
    want = drain_records(clean)
    clean.close()
    chaos_uri = wrap_uri(
        p, "resets=2,short=2,latency_ms=2,spikes=3,errors=1,seed=17"
    )
    chaotic = io_split.IndexedRecordIOSplitter(
        chaos_uri, idx, 0, 1, **sugar
    )
    got = drain_records(chaotic)
    stats = chaotic.io_stats()
    chaotic.close()
    assert got == want, (mode, codec)
    assert stats["faults_injected"] > 0, (mode, codec)
    assert stats["retries"] > 0, (mode, codec)
    # the parallel engine actually carried the window loads (fault://
    # is remote-shaped, so the fetcher engages unless env pinned it
    # off). v1 only: the zlib corpus here is small enough that a
    # window's missing BLOCKS form one contiguous run, which correctly
    # collapses to a single sequential span and skips the engine — the
    # zlib engagement case is pinned by
    # test_chaos_parallel_equals_serial_baseline below.
    if codec is None and io_split._spanfetch.fetch_threads() > 1:
        assert stats["fetch_spans"] > 0, (mode, codec)


def test_chaos_parallel_equals_serial_baseline(tmp_path, monkeypatch):
    """The DMLC_FETCH_THREADS=1 serial baseline and the concurrent
    fetch produce identical bytes UNDER THE SAME chaos spec — the bench
    invariant's correctness half, tier-1-fast."""
    from tests.test_split_gather import (
        drain_records,
        make_indexed_rec,
        records_of,
    )

    from dmlc_core_tpu.io import codec as io_codec

    records = records_of(90, tag="sb")
    p, idx = make_indexed_rec(str(tmp_path), records, codec="zlib")
    uri = wrap_uri(p, "resets=1,short=2,seed=23")
    kw = dict(shuffle="window", seed=4, window=16, merge_gap=0)

    def private_ctx():
        # a per-drain decode context: the process-global decoded-block
        # LRU would serve the second drain from memory and the fetcher
        # would never read a byte
        return io_codec.DecodeContext(
            cache=io_codec.DecodedBlockCache(64 << 20), shared=None
        )

    monkeypatch.setenv("DMLC_FETCH_THREADS", "1")
    serial = io_split.IndexedRecordIOSplitter(
        uri, idx, 0, 1, decode_ctx=private_ctx(), **kw
    )
    want = drain_records(serial)
    serial.close()
    monkeypatch.setenv("DMLC_FETCH_THREADS", "6")
    parallel = io_split.IndexedRecordIOSplitter(
        uri, idx, 0, 1, decode_ctx=private_ctx(), **kw
    )
    got = drain_records(parallel)
    stats = parallel.io_stats()
    parallel.close()
    assert got == want
    assert stats["fetch_spans"] > 0


def test_chaos_query_form_equivalent(golden_rec):
    """The query-param grammar drives the same schedule for direct
    opens (Stream.create passes the full URI to the filesystem)."""
    path, _idx, _recs = golden_rec
    clean = open(path, "rb").read()
    before = retry.stats()
    s = Stream.create(f"fault://{path}?resets=2&seed=9", "r")
    assert s.read(-1) == clean
    s.close()
    assert retry.stats_delta(before)["faults_injected"] == 2


def test_chaos_through_ell_batches_io_stats(golden_rec, tmp_path):
    """The io_stats plumbing end to end: a rowrec dataset staged through
    the fused/generic producer over fault:// surfaces the retry counters
    at the stream level (split -> producer -> bench hook)."""
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import write_rowrec
    from dmlc_core_tpu.staging import BatchSpec, ell_batches

    n, k = 256, 4
    rng = np.random.default_rng(5)
    blk = RowBlock(
        offset=np.arange(0, (n + 1) * k, k, dtype=np.int64),
        label=rng.normal(size=n).astype(np.float32),
        index=rng.integers(0, 50, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "rows.rec")
    with FileStream(rec, "w") as f:
        write_rowrec(f, [blk])
    spec = BatchSpec(batch_size=64, layout="ell", max_nnz=k)

    stream = ell_batches(rec, spec)
    clean = [np.array(b.values) for b in stream]
    stream.close()

    # cap=512: enough read ordinals over the ~13KB file for the
    # scheduled events to land before EOF
    stream = ell_batches(wrap_uri(rec, "resets=2,short=2,seed=13,cap=512"), spec)
    chaos = [np.array(b.values) for b in stream]
    stats = stream.io_stats()
    stream.close()
    assert len(chaos) == len(clean)
    for a, b in zip(clean, chaos):
        np.testing.assert_array_equal(a, b)
    assert stats is not None and stats["retries"] > 0
