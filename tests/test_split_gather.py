"""Vectorized shuffled-read gather (ISSUE 6 tentpole).

The contract under test: every shuffle mode (record/batch/window) rides
ONE windowed emission path whose order is bit-identical to the
pre-change ``shuffle='record'`` loop for the same (seed, epoch) — on v1
AND compressed containers, through the zero-copy ``next_gather_batch``
handoff AND the framed-bytes fallback, via the fused native producer AND
the generic batcher, with fault:// chaos healed by retries — and the
gather path must actually BEAT the legacy per-record loop (the bench
invariant, so the 13x shuffled-read wall can't silently regress).
"""

import os
import time

import numpy as np
import pytest

from dmlc_core_tpu.io import (
    IndexedRecordIOSplitter,
    MemoryStream,
    RecordIOWriter,
    TemporaryDirectory,
)
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.recordio import RecordIOChunkReader
from dmlc_core_tpu.utils import Error


def make_indexed_rec(tmp, records, name="data", codec=None):
    """Write records + sidecar index; codec=None → v1 container,
    else compressed blocks (IndexedRecordIOWriter)."""
    if codec is not None:
        from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
        from dmlc_core_tpu.io.stream import FileStream

        p = os.path.join(tmp, f"{name}.rec")
        idx = os.path.join(tmp, f"{name}.idx")
        with FileStream(p, "w") as d, FileStream(idx, "w") as i:
            w = IndexedRecordIOWriter(d, i, codec=codec, block_bytes=512)
            for r in records:
                w.write_record(r)
            w.flush()
        return p, idx
    ms = MemoryStream()
    w = RecordIOWriter(ms)
    offsets = []
    for r in records:
        offsets.append(ms.tell())
        w.write_record(r)
    p = os.path.join(tmp, f"{name}.rec")
    with open(p, "wb") as f:
        f.write(ms.getvalue())
    idx = os.path.join(tmp, f"{name}.idx")
    with open(idx, "w") as f:
        for i, off in enumerate(offsets):
            f.write(f"{i}\t{off}\n")
    return p, idx


def records_of(n, tag="g"):
    return [f"{tag}rec{i:04d}".encode() * (i % 7 + 1) for i in range(n)]


def drain_records(split):
    out = []
    while True:
        rec = split.next_record()
        if rec is None:
            return out
        out.append(bytes(rec))


def drain_gather(split, n=13):
    """Drain via the zero-copy emission; returns the record payloads in
    emission order (frames parsed back out of the handed views)."""
    out = []
    while True:
        g = split.next_gather_batch(n)
        if g is None:
            return out
        buf, starts, sizes = g
        assert starts.dtype == np.int64 and sizes.dtype == np.int64
        for s, z in zip(starts.tolist(), sizes.tolist()):
            framed = buf[s : s + z].tobytes()
            recs = [bytes(r) for r in RecordIOChunkReader(framed, 0, 1)]
            assert len(recs) == 1  # each slice is one whole record
            out.append(recs[0])


@pytest.mark.parametrize("codec", (None, "zlib"))
@pytest.mark.parametrize("mode", ("record", "window"))
def test_gather_order_bit_identical_to_legacy_record(codec, mode):
    """Acceptance: gather-path epoch order == pre-change
    shuffle='record' for the same (seed, epoch), v1 and compressed
    containers, for both full-permutation modes, both emission paths."""
    records = records_of(137)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records, codec=codec)
        pv, iv = make_indexed_rec(tmp.path, records, name="v1")
        for epoch in (0, 2):
            legacy = IndexedRecordIOSplitter(
                pv, iv, 0, 1, batch_size=9, shuffle="record", seed=5,
                epoch=epoch, legacy_shuffle=True,
            )
            ref = drain_records(legacy)
            legacy.close()
            kw = dict(batch_size=9, shuffle=mode, seed=5, epoch=epoch,
                      window=32)
            s = IndexedRecordIOSplitter(p, idx, 0, 1, **kw)
            assert drain_records(s) == ref, (codec, mode, epoch, "bytes")
            s.close()
            s = IndexedRecordIOSplitter(p, idx, 0, 1, **kw)
            assert drain_gather(s) == ref, (codec, mode, epoch, "gather")
            stats = s.io_stats()
            s.close()
            assert stats["gather_batches"] > 0
            assert stats["gather_fallback_batches"] == 0


def test_batch_mode_gather_equals_bytes_emission():
    """Batch mode rides the same machinery: the gather emission and the
    framed-bytes emission agree record for record, and span-internal
    file order survives."""
    records = records_of(83)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        a = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=10, shuffle="batch", seed=4
        )
        via_bytes = drain_records(a)
        a.close()
        b = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=10, shuffle="batch", seed=4
        )
        via_gather = drain_gather(b)
        b.close()
        assert via_gather == via_bytes
        assert sorted(via_gather) == sorted(records)
        # spans of 10 keep file order internally; the remainder (3
        # records) reads last
        pos = {r: i for i, r in enumerate(records)}
        order = [pos[r] for r in via_gather]
        for s in range(0, 80, 10):
            span = order[s : s + 10]
            assert span == list(range(span[0], span[0] + 10)), s
        assert order[-3:] == [80, 81, 82]


def test_record_mode_resumes_at_any_position():
    """Record mode keeps its resume-anywhere contract on the windowed
    path: skip_records slices the shard-wide window, never replays."""
    records = records_of(101)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=7, shuffle="record", seed=9, epoch=1
        )
        full = drain_records(s)
        s.close()
        for skip in (1, 37, 100, 101):
            s = IndexedRecordIOSplitter(
                p, idx, 0, 1, batch_size=7, shuffle="record", seed=9,
                epoch=1, skip_records=skip,
            )
            assert drain_records(s) == full[skip:], skip
            assert s.records_consumed == len(records), skip
            s.close()


def test_gather_beats_legacy_per_record_loop():
    """Bench invariant (tier-1-safe): on a small synthetic shard the
    gather path must beat the legacy per-record seek loop — the 13x
    shuffled-read wall (BENCH_r05) cannot silently come back. Generous
    margin: the gap is >10x on every host measured; 1.5x catches a
    dead fast path without flaking on a loaded CI box."""
    records = [bytes([i % 251]) * 120 for i in range(20000)]
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)

        def timed(**kw):
            t0 = time.perf_counter()
            s = IndexedRecordIOSplitter(
                p, idx, 0, 1, batch_size=4096, shuffle="record", seed=3,
                **kw,
            )
            n = 0
            while True:
                chunk = s.next_batch_ex(4096)
                if chunk is None:
                    break
                n += 1
            dt = time.perf_counter() - t0
            s.close()
            return dt

        legacy = timed(legacy_shuffle=True)
        gather = timed()
        assert gather * 1.5 < legacy, (gather, legacy)


def test_gather_counters_mirrored_into_telemetry():
    from dmlc_core_tpu.telemetry import default_registry

    reg = default_registry()
    before_b = reg.counter("io.split.gather_batches").value()
    before_by = reg.counter("io.split.gather_bytes").value()
    records = records_of(50)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=8, shuffle="record", seed=1
        )
        drain_gather(s)
        stats = s.io_stats()
        s.close()
        nbytes = os.path.getsize(p)
    assert stats["gather_batches"] > 0
    assert stats["gather_bytes"] == nbytes
    assert (
        reg.counter("io.split.gather_batches").value() - before_b
        == stats["gather_batches"]
    )
    assert (
        reg.counter("io.split.gather_bytes").value() - before_by
        == stats["gather_bytes"]
    )


def test_gather_needs_windowed_mode():
    records = records_of(10)
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(p, idx, 0, 1, batch_size=4)
        assert not s.supports_gather()
        with pytest.raises(Error, match="windowed shuffle"):
            s.next_gather_batch(4)
        s.close()
        s = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=4, shuffle="record",
            legacy_shuffle=True,
        )
        assert not s.supports_gather()
        s.close()


@pytest.mark.parametrize("codec", (None, "zlib"))
@pytest.mark.parametrize("mode", ("record", "batch", "window"))
def test_parallel_fetch_order_identical_to_serial(
    mode, codec, tmp_path, monkeypatch
):
    """ISSUE 9: the concurrent span fetcher on a remote-shaped source
    emits the exact local serial-path epoch order and bytes through the
    zero-copy gather emission, for every shuffle mode on both
    containers — completion-order arrival must never leak into
    emission order."""
    monkeypatch.setenv("DMLC_FETCH_THREADS", "4")  # env-proof parallel
    records = records_of(130, tag="pl")
    p, idx = make_indexed_rec(str(tmp_path), records, codec=codec)
    kw = dict(batch_size=9, shuffle=mode, seed=7, window=28, merge_gap=0)
    ref = IndexedRecordIOSplitter(p, idx, 0, 1, **kw)
    want = drain_gather(ref)
    ref.close()
    s = IndexedRecordIOSplitter(f"fault://seed=5{p}", idx, 0, 1, **kw)
    got = drain_gather(s)
    stats = s.io_stats()
    s.close()
    assert got == want, (mode, codec)
    assert stats["gather_batches"] > 0
    if codec is None:
        # v1 windows plan scattered record spans: the engine must have
        # carried them (zlib block contiguity can collapse to one span)
        assert stats["fetch_spans"] > 0, mode


def test_chaos_gather_identical_to_clean(tmp_path):
    """fault:// chaos with retries > 0: the gather emission heals to
    the exact clean-path order and bytes (record AND window modes)."""
    from dmlc_core_tpu.io.faults import wrap_uri

    records = records_of(90, tag="f")
    p, idx = make_indexed_rec(str(tmp_path), records)
    for mode in ("record", "window"):
        clean = io_split.create(
            f"{p}?index={idx}&shuffle={mode}&seed=6&window=32",
            type="recordio",
        )
        want = drain_gather(clean)
        clean.close()
        uri = wrap_uri(p, "resets=2,short=1,errors=1,seed=11")
        chaotic = io_split.create(
            f"{uri}?index={idx}&shuffle={mode}&seed=6&window=32",
            type="recordio",
        )
        got = drain_gather(chaotic)
        stats = chaotic.io_stats()
        chaotic.close()
        assert got == want, mode
        assert stats["faults_injected"] > 0, mode
        assert stats["retries"] > 0, mode


@pytest.mark.parametrize("codec", (None, "zlib"))
def test_fused_and_generic_batchers_agree_on_gather_order(codec, tmp_path):
    """Staged tensor values: fused gather producer == generic
    parser→FixedShapeBatcher == fused legacy per-record stream, across
    containers (host Batch level; the device golden lives in
    tests/test_staging_sharded.py)."""
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import encode_rows
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.data import native
    from dmlc_core_tpu.staging import BatchSpec, ell_batches

    if not native.HAS_GATHER_ELL:
        pytest.skip("native gather kernel not loaded")
    n, k = 75, 3
    rng = np.random.default_rng(2)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n).astype(np.float32),
        index=rng.integers(0, 99, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / f"t{codec}.rec")
    idx = str(tmp_path / f"t{codec}.idx")
    with FileStream(rec, "w") as d, FileStream(idx, "w") as i:
        w = IndexedRecordIOWriter(
            d, i, **({"codec": codec, "block_bytes": 256} if codec else {})
        )
        for payload in encode_rows(blk):
            w.write_record(payload)
    spec = BatchSpec(batch_size=16, layout="ell", max_nnz=k)
    base = f"{rec}?index={idx}&shuffle=record&seed=12"

    def batches(uri, force_generic=False):
        if force_generic:
            from dmlc_core_tpu.data import create_parser
            from dmlc_core_tpu.staging.batcher import FixedShapeBatcher

            parser = create_parser(uri, 0, 1, type="rowrec")
            src = FixedShapeBatcher(spec).batches(iter(parser))
            out = [
                {kk: np.array(v) for kk, v in b.as_dict().items()}
                for b in src
            ]
            parser.close()
            return out
        s = ell_batches(uri, spec)
        out = [
            {kk: np.array(v) for kk, v in b.as_dict().items()} for b in s
        ]
        stats = s.io_stats()
        s.close()
        return out, stats

    fused, stats = batches(base)
    assert stats["gather_batches"] > 0
    assert stats["gather_fallback_batches"] == 0
    legacy, _ = batches(base + "&legacy_shuffle=1")
    generic = batches(base, force_generic=True)
    assert len(fused) == len(legacy) == len(generic) == -(-n // 16)
    for a, b, c in zip(fused, legacy, generic):
        for key in a:
            np.testing.assert_array_equal(a[key], b[key], err_msg=key)
            np.testing.assert_array_equal(a[key], c[key], err_msg=key)


def test_sharded_fused_gather_coverage(tmp_path):
    """nthread fan-out (ShardedFusedBatches) over a shuffled gather
    stream: disjoint sub-shard permutations, full coverage, summed
    gather counters."""
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import encode_rows
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.staging import BatchSpec, ell_batches

    n, k = 64, 2
    rng = np.random.default_rng(8)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n).astype(np.float32),
        index=rng.integers(0, 40, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "s.rec")
    idx = str(tmp_path / "s.idx")
    with FileStream(rec, "w") as d, FileStream(idx, "w") as i:
        w = IndexedRecordIOWriter(d, i)
        for payload in encode_rows(blk):
            w.write_record(payload)
    spec = BatchSpec(batch_size=8, layout="ell", max_nnz=k)
    s = ell_batches(
        f"{rec}?index={idx}&shuffle=record&seed=3", spec, nthread=2, ring=12
    )
    labels = []
    for b in s:
        labels.extend(np.asarray(b.labels)[: b.n_valid].tolist())
    stats = s.io_stats()
    s.close()
    assert sorted(int(x) for x in labels) == list(range(n))
    assert labels != sorted(labels)  # actually shuffled
    assert stats.get("gather_batches", 0) >= 2  # both sub-shards gathered


def test_gather_numpy_fallback_counts_and_matches(tmp_path, monkeypatch):
    """Stale .so (no gather kernel): the fused consumer re-frames via
    the numpy gather — same staged values, and the emissions are
    COUNTED as fallback batches so the missing fast path is visible in
    io_stats/telemetry."""
    from dmlc_core_tpu.data import native
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import encode_rows
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.staging import BatchSpec, ell_batches

    if not (native.HAS_ELL and native.HAS_GATHER_ELL):
        pytest.skip("native ELL kernels not loaded")
    n, k = 50, 3
    rng = np.random.default_rng(5)
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n).astype(np.float32),
        index=rng.integers(0, 60, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "fb.rec")
    idx = str(tmp_path / "fb.idx")
    with FileStream(rec, "w") as d, FileStream(idx, "w") as i:
        w = IndexedRecordIOWriter(d, i)
        for payload in encode_rows(blk):
            w.write_record(payload)
    spec = BatchSpec(batch_size=16, layout="ell", max_nnz=k)
    uri = f"{rec}?index={idx}&shuffle=record&seed=9"

    def collect():
        s = ell_batches(uri, spec)
        out = [
            {kk: np.array(v) for kk, v in b.as_dict().items()} for b in s
        ]
        stats = s.io_stats()
        s.close()
        return out, stats

    ref, fast_stats = collect()
    assert fast_stats["gather_fallback_batches"] == 0
    monkeypatch.setattr(native, "HAS_GATHER_ELL", False)
    got, slow_stats = collect()
    assert slow_stats["gather_fallback_batches"] > 0
    assert slow_stats["gather_batches"] > 0  # views were still handed out
    assert len(got) == len(ref)
    for a, b in zip(got, ref):
        for key in b:
            np.testing.assert_array_equal(a[key], b[key], err_msg=key)
