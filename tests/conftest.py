"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import (hence conftest, imported first by pytest).
Multi-chip sharding tests validate against this mesh; the driver separately
dry-runs `__graft_entry__.dryrun_multichip` the same way.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: spawns subprocesses with fresh jax imports"
    )
    config.addinivalue_line(
        "markers",
        "jax: imports jax in-process (excluded from sanitizer runs — the "
        "ASan/TSan runtime trips on XLA internals, not on our native core)",
    )

# The axon TPU plugin in this image force-registers itself and wins over
# JAX_PLATFORMS env alone; the config update below reliably pins the test
# session to the virtual 8-device CPU backend.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # data-layer-only environments
    pass


def install_fake_binary(tmp_path, monkeypatch, name, content):
    """Drop an executable stand-in (fake gcloud/ssh/srun) onto PATH —
    shared by the backend integration suites."""
    import os
    import stat

    bindir = tmp_path / "bin"
    bindir.mkdir(exist_ok=True)
    f = bindir / name
    f.write_text(content)
    f.chmod(f.stat().st_mode | stat.S_IXUSR)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    return f
