"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import (hence conftest, imported first by pytest).
Multi-chip sharding tests validate against this mesh; the driver separately
dry-runs `__graft_entry__.dryrun_multichip` the same way.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _refresh_native() -> None:
    """`make native` staleness gate (ISSUE 6 satellite): when
    native/fastparse.cc is newer than the prebuilt .so, rebuild BEFORE
    anything imports dmlc_core_tpu.data.native — otherwise the native
    parity suites (and every fused-kernel test) silently validate last
    round's binary. No toolchain → skip the rebuild with a visible
    reason on stderr; the source-hash stamp still flags the stale .so
    wherever it matters (bench.ensure_native refuses it outright)."""
    import shutil
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(root, "native", "fastparse.cc")
    so = os.path.join(root, "native", "libdmlc_tpu_native.so")
    if not os.path.exists(src):
        return
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return
    make = shutil.which("make")
    cxx = shutil.which(os.environ.get("CXX", "g++"))
    if not make or not cxx:
        sys.stderr.write(
            "[conftest] SKIPPING native rebuild: fastparse.cc is newer "
            "than libdmlc_tpu_native.so but no make/g++ toolchain is "
            "available — native suites run against the existing binary\n"
        )
        return
    proc = subprocess.run(
        [make, "-C", os.path.join(root, "native")],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        sys.stderr.write(
            "[conftest] native rebuild FAILED; tests run against the "
            "stale binary:\n" + (proc.stdout + proc.stderr)[-2000:] + "\n"
        )


_refresh_native()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: spawns subprocesses with fresh jax imports"
    )
    config.addinivalue_line(
        "markers",
        "jax: imports jax in-process (excluded from sanitizer runs — the "
        "ASan/TSan runtime trips on XLA internals, not on our native core)",
    )
    config.addinivalue_line(
        "markers",
        "blockcache: needs POSIX shared memory AND UNIX-domain sockets "
        "(the host-shared decoded-block cache daemon, io/blockcache.py); "
        "skipped with a visible reason where either is unavailable",
    )


def _blockcache_unsupported():
    """Reason string when this host cannot run the shared block-cache
    daemon (no /dev/shm-backed POSIX shm, or no UNIX sockets — e.g.
    some containers and non-POSIX platforms); None when it can."""
    import socket
    import tempfile

    try:
        from dmlc_core_tpu.io.blockcache import _ShmSegment

        seg = _ShmSegment(f"dmlcprobe-{os.getpid()}", create=True, size=8)
        try:
            seg.buf[:2] = b"ok"
        finally:
            seg.close()
            seg.unlink()
    except Exception as e:
        return f"POSIX shared memory unavailable: {e!r}"
    try:
        with tempfile.TemporaryDirectory() as d:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.bind(os.path.join(d, "probe.sock"))
            finally:
                s.close()
    except Exception as e:
        return f"UNIX-domain sockets unavailable: {e!r}"
    return None


def pytest_collection_modifyitems(config, items):
    reason = False  # tri-state: False = not probed yet
    for item in items:
        if item.get_closest_marker("blockcache") is None:
            continue
        if reason is False:
            reason = _blockcache_unsupported()
        if reason:
            import pytest

            item.add_marker(pytest.mark.skip(
                reason=f"shared block-cache daemon unsupported: {reason}"
            ))

# The axon TPU plugin in this image force-registers itself and wins over
# JAX_PLATFORMS env alone; the config update below reliably pins the test
# session to the virtual 8-device CPU backend.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # data-layer-only environments
    pass


def install_fake_binary(tmp_path, monkeypatch, name, content):
    """Drop an executable stand-in (fake gcloud/ssh/srun) onto PATH —
    shared by the backend integration suites."""
    import os
    import stat

    bindir = tmp_path / "bin"
    bindir.mkdir(exist_ok=True)
    f = bindir / name
    f.write_text(content)
    f.chmod(f.stat().st_mode | stat.S_IXUSR)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    return f
