"""The URI CLI (python -m dmlc_core_tpu.tools) — parity with the
reference's Tier-2 standalone test programs: filesys_test.cc:8-40
(ls/cat/cp), split_test.cc:8-24 (stream a shard), recordio_test.cc
(pack/unpack), plus the rowrec conversion the staging path needs."""

import os
import subprocess
import sys

import numpy as np
import pytest

from dmlc_core_tpu import tools
from dmlc_core_tpu.data import create_row_block_iter
from dmlc_core_tpu.staging import BatchSpec, ell_batches


def run_cli(argv, capsys):
    rc = tools.main(argv)
    out = capsys.readouterr()
    return rc, out.out, out.err


@pytest.fixture()
def libsvm_file(tmp_path):
    p = tmp_path / "train.libsvm"
    rng = np.random.default_rng(5)
    lines = []
    for i in range(40):
        feats = " ".join(
            f"{j}:{rng.normal():.4f}" for j in sorted(rng.choice(20, 3, replace=False))
        )
        lines.append(f"{i % 2} {feats}")
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_ls_and_cat_and_cp(tmp_path, capsys):
    (tmp_path / "a.txt").write_text("hello\n")
    (tmp_path / "b.txt").write_text("world\n")
    rc, out, _ = run_cli(["ls", str(tmp_path)], capsys)
    assert rc == 0
    assert "a.txt" in out and "b.txt" in out and f"{6:>12}" in out

    rc, out, _ = run_cli(["cat", str(tmp_path / "a.txt")], capsys)
    assert rc == 0 and out == "hello\n"

    rc, _, err = run_cli(
        ["cp", str(tmp_path / "a.txt"), str(tmp_path / "c.txt")], capsys
    )
    assert rc == 0 and "6 bytes" in err
    assert (tmp_path / "c.txt").read_text() == "hello\n"


def test_split_shard_counts(libsvm_file, capsys):
    total = 0
    for part in range(3):
        rc, _, err = run_cli(
            ["split", libsvm_file, str(part), "3"], capsys
        )
        assert rc == 0
        total += int(err.split(":")[1].split()[0])
    assert total == 40


def test_split_dump_roundtrips_lines(libsvm_file, capsys):
    rc, out, _ = run_cli(["split", libsvm_file, "0", "1", "--dump"], capsys)
    assert rc == 0
    assert out.splitlines() == open(libsvm_file).read().splitlines()


def test_recordio_pack_unpack_roundtrip(tmp_path, capsys):
    src = tmp_path / "lines.txt"
    src.write_text("alpha\nbeta\ngamma\n")
    rec = str(tmp_path / "lines.rec")
    rc, _, err = run_cli(["recordio", "pack", str(src), rec], capsys)
    assert rc == 0 and "packed 3 records" in err
    rc, out, err = run_cli(["recordio", "unpack", rec], capsys)
    assert rc == 0 and "unpacked 3 records" in err
    assert out == "alpha\nbeta\ngamma\n"


def test_recordio_pack_blank_line_semantics(tmp_path, capsys):
    """Blank lines collapse, matching reference LineSplitter (runs of
    \\n/\\r are one separator, line_split.cc:42-44) — parity, chosen and
    documented rather than accidental."""
    src = tmp_path / "lines.txt"
    src.write_text("gamma\n\ndelta\n")
    rec = str(tmp_path / "lines.rec")
    rc, _, err = run_cli(["recordio", "pack", str(src), rec], capsys)
    assert rc == 0 and "packed 2 records" in err
    rc, out, _ = run_cli(["recordio", "unpack", rec], capsys)
    assert rc == 0 and out == "gamma\ndelta\n"


def test_recordio_pack_requires_dst(tmp_path, capsys):
    src = tmp_path / "x.txt"
    src.write_text("a\n")
    rc, _, err = run_cli(["recordio", "pack", str(src)], capsys)
    assert rc == 2 and "dst" in err


def test_rowrec_conversion_feeds_staging(libsvm_file, tmp_path, capsys):
    """libsvm → .rec+index via the CLI, then read back through both the
    parser path and the fused ELL staging path with the index sugar."""
    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.rec.idx")
    rc, _, err = run_cli(
        ["rowrec", libsvm_file, rec, "--format", "libsvm", "--index", idx],
        capsys,
    )
    assert rc == 0 and "wrote 40 rows" in err

    it = create_row_block_iter(rec + "?format=rowrec")
    labels = [x for b in it for x in np.asarray(b.label).tolist()]
    assert sorted(labels) == sorted(float(i % 2) for i in range(40))

    stream = ell_batches(
        f"{rec}?index={idx}", BatchSpec(batch_size=8, layout="ell", max_nnz=3)
    )
    n = sum(int(b.n_valid) for b in stream)
    stream.close()
    assert n == 40


def test_rowrec_sharded_conversion_covers_exactly(libsvm_file, tmp_path, capsys):
    """--part/--num-parts converts record-aligned shards: the shard
    .rec files together hold every row exactly once (parallel
    conversion of large datasets, one part per worker)."""
    labels = []
    for part in range(3):
        rec = str(tmp_path / f"s{part}.rec")
        rc, _, err = run_cli(
            ["rowrec", libsvm_file, rec, "--format", "libsvm",
             "--part", str(part), "--num-parts", "3"],
            capsys,
        )
        assert rc == 0
        it = create_row_block_iter(rec + "?format=rowrec")
        labels.extend(x for b in it for x in np.asarray(b.label).tolist())
    assert sorted(labels) == sorted(float(i % 2) for i in range(40))


def test_module_entrypoint_runs():
    proc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.tools", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    assert "ls" in proc.stdout and "rowrec" in proc.stdout


def test_error_paths_return_nonzero(tmp_path, capsys):
    rc, _, err = run_cli(["cat", str(tmp_path / "missing.txt")], capsys)
    assert rc == 1 and "error:" in err


def test_dump_inverts_rowrec_conversion(libsvm_file, tmp_path, capsys):
    """libsvm → rowrec → dump → parse again must preserve every row's
    label/indices/values (text→rec→text round trip, %.9g exact for f32);
    --limit and sharding behave."""
    rec = str(tmp_path / "d.rec")
    rc, _, _ = run_cli(["rowrec", libsvm_file, rec, "--format", "libsvm"],
                       capsys)
    assert rc == 0
    rc, out, err = run_cli(["dump", rec], capsys)
    assert rc == 0 and "dumped 40 rows" in err
    back = str(tmp_path / "back.libsvm")
    open(back, "w").write(out)

    def blocks(uri):
        it = create_row_block_iter(uri)
        offs, labels, idxs, vals = [0], [], [], []
        for b in it:
            labels.extend(np.asarray(b.label).tolist())
            idxs.extend(np.asarray(b.index).tolist())
            vals.extend(np.asarray(b.value).tolist())
        return labels, idxs, vals

    l1, i1, v1 = blocks(libsvm_file + "?format=libsvm")
    l2, i2, v2 = blocks(back + "?format=libsvm")
    assert l1 == l2 and i1 == i2
    np.testing.assert_allclose(v1, v2, rtol=0, atol=0)

    rc, out, err = run_cli(["dump", rec, "--limit", "5"], capsys)
    assert rc == 0 and "dumped 5 rows (limit)" in err
    assert len(out.splitlines()) == 5
    rc, out, _ = run_cli(["dump", rec, "--part", "1", "--num-parts", "2"],
                         capsys)
    assert rc == 0 and len(out.splitlines()) == 20


def test_dump_fidelity_edge_cases(tmp_path, capsys):
    """Binary features dump as bare indices (value=None must not crash),
    f32 labels/weights keep exact bits (%.9g), qid and libfm fields are
    preserved."""
    svm = tmp_path / "x.libsvm"
    svm.write_text(
        "0.123456789:2.5 qid:7 3 9 12\n"   # weight, qid, binary features
        "1 0:0.25 5:0.5\n"
    )
    rc, out, err = run_cli(["dump", f"{svm}?format=libsvm"], capsys)
    assert rc == 0 and "dumped 2 rows" in err
    l1, l2 = out.splitlines()
    # value presence is block-level (reference semantics): a mixed chunk
    # materializes 1.0 for binary features — equivalent, still faithful
    assert l1 == "0.123456791:2.5 qid:7 3:1 9:1 12:1"  # f32-exact label
    # qid defaults to 0 for rows without one (reference atoll semantics),
    # so the faithful dump carries qid:0 — re-parsing gives identical data
    assert l2 == "1 qid:0 0:0.25 5:0.5"

    # an all-binary chunk has value=None → bare indices, no crash
    binsvm = tmp_path / "b.libsvm"
    binsvm.write_text("1 3 9\n0 2\n")
    rc, out, _ = run_cli(["dump", f"{binsvm}?format=libsvm"], capsys)
    assert rc == 0
    assert out.splitlines() == ["1 3 9", "0 2"]

    fm = tmp_path / "x.libfm"
    fm.write_text("1 2:30:0.75 4:50\n")
    rc, out, err = run_cli(["dump", f"{fm}?format=libfm"], capsys)
    assert rc == 0
    assert out.splitlines() == ["1 2:30:0.75 4:50:1"]


def test_recordio_pack_codec_and_recompress_roundtrip(tmp_path, capsys):
    """--codec packs compressed blocks; recompress converts v1 ↔
    compressed in one stream pass and every direction round-trips;
    the fresh --index sidecar drives indexed reads of the output."""
    from dmlc_core_tpu.io import split as io_split
    from dmlc_core_tpu.io.recordio import RecordIOReader
    from dmlc_core_tpu.io.stream import FileStream

    src = tmp_path / "lines.txt"
    lines = [f"row-{i}-{'x' * (i % 17)}" for i in range(120)]
    src.write_text("\n".join(lines) + "\n")
    v1 = str(tmp_path / "v1.rec")
    rc, _, err = run_cli(["recordio", "pack", str(src), v1], capsys)
    assert rc == 0 and "packed 120" in err

    comp = str(tmp_path / "comp.rec")
    idx = comp + ".idx"
    rc, _, err = run_cli(
        ["recompress", v1, comp, "--codec", "zlib", "--index", idx], capsys
    )
    assert rc == 0 and "recompressed 120 records" in err
    assert os.path.getsize(comp) < os.path.getsize(v1)
    with FileStream(comp, "r") as f:
        assert [r.decode() for r in RecordIOReader(f)] == lines
    sp = io_split.create(f"{comp}?index={idx}&shuffle=window&window=32",
                         0, 1, type="recordio", threaded=False)
    assert sorted(bytes(r).decode() for r in sp) == sorted(lines)
    sp.close()

    # back to v1: byte-identical to the original pack output
    back = str(tmp_path / "back.rec")
    rc, _, err = run_cli(["recompress", comp, back, "--codec", "none"],
                         capsys)
    assert rc == 0
    assert open(back, "rb").read() == open(v1, "rb").read()

    # unpack reads compressed files transparently
    rc, out, err = run_cli(["recordio", "unpack", comp], capsys)
    assert rc == 0 and "unpacked 120" in err
    assert out.splitlines() == lines

    # direct compressed pack too
    packed = str(tmp_path / "packed.rec")
    rc, _, err = run_cli(
        ["recordio", "pack", str(src), packed, "--codec", "gzip",
         "--level", "1"],
        capsys,
    )
    assert rc == 0 and "packed 120" in err
    with FileStream(packed, "r") as f:
        assert [r.decode() for r in RecordIOReader(f)] == lines


def test_rowrec_codec_feeds_staging(libsvm_file, tmp_path, capsys):
    """rowrec --codec: compressed shard + block index still feed both
    the parser path and the fused ELL staging path unchanged."""
    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.rec.idx")
    rc, _, err = run_cli(
        ["rowrec", libsvm_file, rec, "--format", "libsvm",
         "--index", idx, "--codec", "zlib"],
        capsys,
    )
    assert rc == 0 and "wrote 40 rows" in err
    assert ":" in open(idx).read().split()[1]  # block:in-offset sidecar

    it = create_row_block_iter(rec + "?format=rowrec")
    labels = [x for b in it for x in np.asarray(b.label).tolist()]
    assert sorted(labels) == sorted(float(i % 2) for i in range(40))

    stream = ell_batches(
        f"{rec}?index={idx}", BatchSpec(batch_size=8, layout="ell", max_nnz=3)
    )
    n = sum(int(b.n_valid) for b in stream)
    stream.close()
    assert n == 40


def test_info_reports_features(capsys):
    """`tools info` emits the build_info report: kernel flags present and
    consistent with the loaded native module (base.h feature macros as
    runtime facts, reference include/dmlc/base.h)."""
    import json

    from dmlc_core_tpu.data import native as native_mod

    rc, out, _ = run_cli(["info"], capsys)
    assert rc == 0
    info = json.loads(out)
    assert info["native_available"] == native_mod.AVAILABLE
    assert info["fused_kernels"]["libfm_ell"] == native_mod.HAS_LIBFM_ELL
    assert set(info["fused_kernels"]) == {
        "libsvm_dense", "csv_dense", "rowrec_ell", "libfm_ell",
        "libsvm_ell",
    }
    assert info["fused_kernels"]["libsvm_ell"] == native_mod.HAS_LIBSVM_ELL
    # codec availability rides the same report (deploy targets can be
    # checked remotely before shipping compressed shards)
    from dmlc_core_tpu.io.codec import available_codecs

    assert info["codecs"] == available_codecs()
    assert {"raw", "zlib", "gzip"} <= set(info["codecs"])


def test_bad_shard_args_are_cli_errors(libsvm_file, tmp_path, capsys):
    """Out-of-range --part/--num-parts must be a diagnosed CLI error
    (shared factory check), not a traceback or a silent empty shard."""
    rec = str(tmp_path / "x.rec")
    for extra in (["--num-parts", "0"], ["--part", "3", "--num-parts", "3"],
                  ["--part", "-1"]):
        rc, _, err = run_cli(
            ["rowrec", libsvm_file, rec, "--format", "libsvm", *extra],
            capsys,
        )
        assert rc == 1 and "invalid shard" in err, (extra, err)
    rc, _, err = run_cli(["split", libsvm_file, "2", "2"], capsys)
    assert rc == 1 and "invalid shard" in err


def test_ckpt_ls_show_prune(tmp_path, capsys):
    """tools ckpt: list steps with layout, inspect a tree's shapes,
    prune to a retention count — over both checkpoint layouts."""
    import json

    import numpy as np

    from dmlc_core_tpu.checkpoint import Checkpointer

    base = str(tmp_path / "cks")
    ck = Checkpointer(base, keep=10, process_index=0)
    for s in (1, 2, 3):
        ck.save(
            s,
            {"w": np.full((4, 2), s, np.float32), "step": s},
            meta={"epoch": s, "records": 64 * s} if s == 3 else None,
        )

    rc, out, _ = run_cli(["ckpt", "ls", base], capsys)
    listing = json.loads(out)
    assert rc == 0 and [e["step"] for e in listing] == [1, 2, 3]
    assert all(e["layout"] == "single" and e["bytes"] > 0 for e in listing)

    rc, out, _ = run_cli(["ckpt", "show", base], capsys)
    shown = json.loads(out)
    assert rc == 0 and shown["step"] == 3
    assert shown["tree"]["w"] == "float32[4, 2]"
    # the data position rides the inspection surface (§5.4)
    assert shown["meta"] == {"epoch": 3, "records": 192}

    rc, out, _ = run_cli(["ckpt", "show", base, "--step", "1"], capsys)
    shown1 = json.loads(out)
    assert shown1["step"] == 1 and "meta" not in shown1

    # --keep 0 disables pruning (Checkpointer semantics), never a
    # silent destructive default
    rc, out, _ = run_cli(["ckpt", "prune", base, "--keep", "0"], capsys)
    pruned = json.loads(out)
    assert rc == 0 and pruned["kept"] == [1, 2, 3] and pruned["removed"] == []

    rc, out, _ = run_cli(["ckpt", "prune", base, "--keep", "2"], capsys)
    pruned = json.loads(out)
    assert rc == 0 and pruned["kept"] == [2, 3] and pruned["removed"] == [1]

    rc, out, err = run_cli(["ckpt", "show", base, "--step", "9"], capsys)
    assert rc == 1 and "error:" in err and "step 9" in err

    rc, out, err = run_cli(
        ["ckpt", "show", str(tmp_path / "empty")], capsys
    )
    assert rc == 1 and "error:" in err and "None" not in err


def test_ckpt_ls_sharded_layout(tmp_path, capsys):
    import json

    import numpy as np

    from dmlc_core_tpu.checkpoint import Checkpointer

    base = str(tmp_path / "cks")
    Checkpointer(base, sharded=True).save(7, {"w": np.ones(6, np.float32)})
    rc, out, _ = run_cli(["ckpt", "ls", base], capsys)
    (entry,) = json.loads(out)
    assert rc == 0 and entry["layout"] == "sharded" and entry["step"] == 7
    rc, out, _ = run_cli(["ckpt", "show", base], capsys)
    assert json.loads(out)["tree"]["w"] == "float32[6]"
