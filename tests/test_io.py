"""Tests for streams, filesystems, serializer, RecordIO.

Modeled on reference test/unittest/unittest_serializer.cc,
unittest_tempdir.cc, test/recordio_test.cc (SURVEY §4).
"""

import os
import struct

import numpy as np
import pytest

from dmlc_core_tpu.io import (
    KMAGIC,
    FileSystem,
    LocalFileSystem,
    MemoryFileSystem,
    MemoryStream,
    RecordIOChunkReader,
    RecordIOReader,
    RecordIOWriter,
    SeekStream,
    Stream,
    TemporaryDirectory,
    URI,
    URISpec,
    serializer,
)
from dmlc_core_tpu.utils import Error


# -- URI ---------------------------------------------------------------------
def test_uri_parse():
    u = URI("gs://bucket/a/b.txt")
    assert u.protocol == "gs://" and u.host == "bucket" and u.path == "/a/b.txt"
    assert u.name == "gs://bucket/a/b.txt"
    u2 = URI("/local/path")
    assert u2.protocol == "" and u2.path == "/local/path"
    u3 = URI("file:///local/path")
    assert u3.protocol == "file://" and u3.path == "/local/path"


def test_urispec_rejoin_roundtrip_property():
    """rejoin_query must be the exact inverse of URISpec's query parse
    for every args dict free of the separator characters — the whole
    URI-sugar machinery (split factory, parser registry, fused
    producers) re-serializes through this pair, so drift would silently
    drop dataset options."""
    hyp = pytest.importorskip("hypothesis")  # baked into the image;
    given, settings = hyp.given, hyp.settings  # skip cleanly elsewhere
    st = pytest.importorskip("hypothesis.strategies")

    from dmlc_core_tpu.io.uri import rejoin_query

    key = st.text(
        alphabet=st.characters(blacklist_characters="?&=#", min_codepoint=33,
                               max_codepoint=126),
        min_size=1, max_size=12,
    )
    val = st.text(
        alphabet=st.characters(blacklist_characters="?&#", min_codepoint=32,
                               max_codepoint=126),
        min_size=0, max_size=20,
    )

    @settings(max_examples=200, deadline=None)
    @given(st.dictionaries(key, val, max_size=6))
    def check(args):
        uri = "gs://b/data.rec" + rejoin_query(args) + "#cachefile"
        spec = URISpec(uri)
        assert spec.uri == "gs://b/data.rec"
        assert spec.args == args
        assert spec.cache_file == "cachefile"

    check()


def test_urispec_sugar():
    s = URISpec("gs://b/train.libsvm?format=libsvm&nthread=4#cache")
    assert s.uri == "gs://b/train.libsvm"
    assert s.args == {"format": "libsvm", "nthread": "4"}
    assert s.cache_file == "cache"
    sharded = URISpec("f.txt#cache", part_index=2, num_parts=8)
    assert sharded.cache_file == "cache.split8.part2"  # reference uri_spec.h:42-75
    plain = URISpec("f.txt")
    assert plain.uri == "f.txt" and plain.args == {} and plain.cache_file == ""


# -- streams & filesystems ---------------------------------------------------
def test_local_stream_roundtrip():
    with TemporaryDirectory() as tmp:
        path = os.path.join(tmp.path, "x.bin")
        with Stream.create(path, "w") as s:
            s.write(b"hello ")
        with Stream.create(path, "a") as s:
            s.write(b"world")
        s = SeekStream.create_for_read(path)
        assert s.read() == b"hello world"
        s.seek(6)
        assert s.read(5) == b"world" and s.tell() == 11
        s.close()


def test_stream_create_allow_null():
    assert Stream.create("/nonexistent/nope", "r", allow_null=True) is None
    with pytest.raises(Exception):
        Stream.create("/nonexistent/nope", "r")


def test_local_filesystem_listing():
    with TemporaryDirectory() as tmp:
        os.makedirs(os.path.join(tmp.path, "sub"))
        for name in ("a.txt", "b.txt", "sub/c.txt"):
            with open(os.path.join(tmp.path, name), "wb") as f:
                f.write(b"x" * 3)
        fs = FileSystem.get_instance(tmp.path)
        assert isinstance(fs, LocalFileSystem)
        infos = fs.list_directory(tmp.path)
        names = [os.path.basename(i.path) for i in infos]
        assert names == ["a.txt", "b.txt", "sub"]
        assert [i.type for i in infos] == ["file", "file", "directory"]
        rec = fs.list_directory_recursive(tmp.path)
        assert sorted(os.path.basename(i.path) for i in rec) == ["a.txt", "b.txt", "c.txt"]
        info = fs.get_path_info(os.path.join(tmp.path, "a.txt"))
        assert info.size == 3 and info.type == "file"
        assert fs.exists(os.path.join(tmp.path, "a.txt"))
        assert not fs.exists(os.path.join(tmp.path, "zz.txt"))


def test_memory_filesystem():
    MemoryFileSystem.reset()
    with Stream.create("mem://bkt/dir/a.txt", "w") as s:
        s.write(b"alpha")
    with Stream.create("mem://bkt/dir/b.txt", "w") as s:
        s.write(b"beta!")
    fs = FileSystem.get_instance("mem://bkt")
    infos = fs.list_directory("mem://bkt/dir")
    assert [(i.path, i.size) for i in infos] == [
        ("mem://bkt/dir/a.txt", 5),
        ("mem://bkt/dir/b.txt", 5),
    ]
    assert Stream.create("mem://bkt/dir/a.txt", "r").read() == b"alpha"
    with Stream.create("mem://bkt/dir/a.txt", "a") as s:
        s.write(b"++")
    assert Stream.create("mem://bkt/dir/a.txt", "r").read() == b"alpha++"
    assert fs.get_path_info("mem://bkt/dir").type == "directory"
    with pytest.raises(Error):
        Stream.create("mem://bkt/missing", "r")


def test_tempdir_cleanup():
    t = TemporaryDirectory()
    p = t.path
    assert os.path.isdir(p)
    with open(os.path.join(p, "f"), "w") as f:
        f.write("x")
    t.cleanup()
    assert not os.path.exists(p)


# -- serializer --------------------------------------------------------------
def test_serializer_scalars_and_bytes():
    s = MemoryStream()
    serializer.write_scalar(s, 42, "uint32")
    serializer.write_scalar(s, -7, "int64")
    serializer.write_scalar(s, 1.5, "float32")
    serializer.write_bytes(s, b"abc")
    s.seek(0)
    assert serializer.read_scalar(s, "uint32") == 42
    assert serializer.read_scalar(s, "int64") == -7
    assert serializer.read_scalar(s, "float32") == 1.5
    assert serializer.read_bytes(s) == b"abc"


def test_serializer_wire_format_is_little_endian_uint64_sizes():
    # compatibility pin: string = uint64 LE length + bytes (reference
    # serializer.h:176-190)
    s = MemoryStream()
    serializer.write_str(s, "hi")
    assert s.getvalue() == struct.pack("<Q", 2) + b"hi"


def test_serializer_composite_roundtrip():
    # reference unittest_serializer.cc: nested STL graphs roundtrip
    obj = {
        "name": "test",
        "ids": [1, 2, 3],
        "pairs": [(1, "a"), (2, "b")],
        "blob": b"\x00\xff",
        "f": 3.25,
        "flag": True,
        "none": None,
        "arr": np.arange(6, dtype=np.float32).reshape(2, 3),
    }
    s = MemoryStream()
    serializer.save(s, obj)
    s.seek(0)
    back = serializer.load(s)
    assert back["name"] == "test" and back["ids"] == [1, 2, 3]
    assert back["pairs"] == [(1, "a"), (2, "b")]
    assert back["blob"] == b"\x00\xff" and back["f"] == 3.25
    assert back["flag"] is True and back["none"] is None
    np.testing.assert_array_equal(back["arr"], obj["arr"])
    assert back["arr"].dtype == np.float32


def test_serializer_ndarray_dtypes():
    for dtype in ("uint8", "int32", "uint32", "int64", "float32", "float64"):
        arr = np.array([0, 1, 255], dtype=dtype)
        s = MemoryStream()
        serializer.write_ndarray(s, arr)
        s.seek(0)
        back = serializer.read_ndarray(s)
        np.testing.assert_array_equal(back, arr)
        assert back.dtype == arr.dtype


# -- RecordIO ----------------------------------------------------------------
def test_recordio_frame_layout_golden():
    """Byte-level golden check derived from the format spec
    (reference recordio.h:16-45): simple record has no collisions."""
    s = MemoryStream()
    RecordIOWriter(s).write_record(b"abcde")
    raw = s.getvalue()
    magic, lrec = struct.unpack("<II", raw[:8])
    assert magic == KMAGIC
    assert (lrec >> 29) & 7 == 0 and lrec & ((1 << 29) - 1) == 5
    assert raw[8:13] == b"abcde" and raw[13:16] == b"\x00\x00\x00"
    assert len(raw) == 16


def test_recordio_roundtrip_with_magic_collisions():
    """The hard case (reference recordio.cc:11-51): payload contains the
    magic word at aligned and unaligned offsets."""
    magic = struct.pack("<I", KMAGIC)
    records = [
        b"",
        b"x",
        b"hello world",
        magic,                      # exactly magic
        magic + magic,              # two aligned collisions
        b"abcd" + magic + b"efgh",  # aligned collision mid-record
        b"ab" + magic + b"cd",      # UNaligned: must not split
        magic * 5 + b"tail",
        bytes(range(256)) * 11,
    ]
    s = MemoryStream()
    w = RecordIOWriter(s)
    for r in records:
        w.write_record(r)
    assert w.except_counter == 1 + 2 + 1 + 5
    s.seek(0)
    got = list(RecordIOReader(s))
    assert got == records


def test_recordio_rejects_oversize():
    w = RecordIOWriter(MemoryStream())
    class FakeBytes(bytes):  # avoid allocating 512MB
        def __len__(self):
            return 1 << 29
    with pytest.raises(Error):
        w.write_record(FakeBytes())


def test_recordio_chunk_reader_partition():
    """RecordIOChunkReader splits a chunk among threads with no loss/dup
    (reference recordio.cc:101-156, test pattern unittest_inputsplit.cc)."""
    magic = struct.pack("<I", KMAGIC)
    records = [f"record-{i}".encode() * (i % 7 + 1) for i in range(57)]
    records += [magic + b"x", magic * 3]
    s = MemoryStream()
    w = RecordIOWriter(s)
    for r in records:
        w.write_record(r)
    chunk = s.getvalue()
    for nthread in (1, 2, 3, 8):
        got = []
        for tid in (range(nthread)):
            reader = RecordIOChunkReader(chunk, tid, nthread)
            got.extend(bytes(r) for r in reader)
        assert got == records, f"nthread={nthread}"


def test_recordio_reader_detects_corruption():
    s = MemoryStream()
    RecordIOWriter(s).write_record(b"data")
    raw = bytearray(s.getvalue())
    raw[0] ^= 0xFF  # corrupt magic
    with pytest.raises(Error, match="magic"):
        RecordIOReader(MemoryStream(bytes(raw))).next_record()
    with pytest.raises(Error, match="truncated"):
        RecordIOReader(MemoryStream(s.getvalue()[:6])).next_record()


# -- StreamIO / wrap_text: the dmlc::ostream/istream adapters ----------------

def test_streamio_readinto_and_buffered_reader():
    import io as pyio

    from dmlc_core_tpu.io import StreamIO

    s = MemoryStream(b"hello world, " * 100)
    raw = StreamIO(s, mode="r")
    assert raw.readable() and not raw.writable() and raw.seekable()
    buf = bytearray(5)
    assert raw.readinto(buf) == 5 and bytes(buf) == b"hello"
    reader = pyio.BufferedReader(StreamIO(MemoryStream(b"abc\ndef\n")))
    assert reader.readline() == b"abc\n"
    assert reader.read() == b"def\n"


def test_streamio_write_and_seek():
    import io as pyio

    from dmlc_core_tpu.io import StreamIO

    s = MemoryStream()
    raw = StreamIO(s, mode="w")
    assert raw.writable() and not raw.readable()
    with pyio.BufferedWriter(raw) as w:
        w.write(b"0123456789")
    assert s.getvalue() == b"0123456789"
    # mode is enforced io-protocol-style (UnsupportedOperation is an
    # OSError): a read-only wrapper must not write and vice versa, even
    # though MemoryStream itself can do both
    with pytest.raises(OSError):
        StreamIO(MemoryStream(b"x"), mode="r").write(b"y")
    with pytest.raises(OSError):
        StreamIO(MemoryStream(b"x"), mode="w").readinto(bytearray(1))
    rw = StreamIO(MemoryStream(b"0123456789"), mode="rw")
    rw.seek(4)
    assert rw.read(2) == b"45"
    rw.seek(-2, 1)  # SEEK_CUR
    assert rw.tell() == 4
    with pytest.raises(OSError):
        rw.seek(0, 2)  # SEEK_END unsupported


def test_wrap_text_round_trip_and_csv_over_mem_uri():
    import csv

    from dmlc_core_tpu.io import MemoryFileSystem, wrap_text

    MemoryFileSystem.reset()
    try:
        with wrap_text(Stream.create("mem://t/rows.csv", "w"), "w") as f:
            csv.writer(f).writerows([["a", 1], ["b", 2]])
        with wrap_text(Stream.create("mem://t/rows.csv", "r")) as f:
            rows = list(csv.reader(f))
        assert rows == [["a", "1"], ["b", "2"]]
    finally:
        MemoryFileSystem.reset()


def test_streamio_close_stream_ownership():
    from dmlc_core_tpu.io import StreamIO

    class Tracked(MemoryStream):
        closed_count = 0

        def close(self):
            Tracked.closed_count += 1
            super().close()

    s = Tracked(b"x")
    StreamIO(s).close()  # caller-owned by default (reference semantics)
    assert Tracked.closed_count == 0
    StreamIO(s, close_stream=True).close()
    assert Tracked.closed_count == 1
