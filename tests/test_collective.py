"""Worker-side collective engine (tracker/collective.py): allreduce /
broadcast over the tracker topology with rabit-parity fault tolerance.

Layers covered, cheapest first:
- ``reference_allreduce`` self-consistency (the bit-identity oracle);
- real-socket jobs (threads + one RabitTracker, the test_tracker.py
  pattern): tree AND ring paths bit-identical to the reference for
  sum/max/min across 2-8 ranks, broadcast from every root, barrier,
  custom reducers, dtype coverage, uneven ring segments;
- fault tolerance in-process: seeded mid-round link resets healed by
  reset-flood + re-rendezvous, a dead worker re-joining via the jobid
  memo, bootstrap-from-peer ``load_checkpoint``, multi-round replay
  through the survivors' result caches, instant peer-death notification
  via the tracker DeathWatch (no timeout discovery);
- the chaos drill: a REAL 3-process SGD job under the supervisor,
  one worker SIGKILLed mid-round by the fault injector, relaunched,
  bootstrapped from a peer — final model bit-identical to a clean run.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from dmlc_core_tpu.tracker import collective as coll_mod
from dmlc_core_tpu.tracker.client import RabitWorker
from dmlc_core_tpu.tracker.collective import (
    Collective,
    DeathWatch,
    active_watch,
    notify_task_failure,
    reference_allreduce,
    set_active_watch,
)
from dmlc_core_tpu.tracker.protocol import FramedSocket
from dmlc_core_tpu.tracker.supervisor import Supervisor
from dmlc_core_tpu.tracker.tracker import RabitTracker
from dmlc_core_tpu.utils.logging import Error

REPO = Path(__file__).resolve().parent.parent


# -- harness -----------------------------------------------------------------


def run_collective(n, body, io_timeout=30.0, **coll_kw):
    """One real-socket job: a tracker plus ``n`` threaded workers, each
    running ``body(coll, rank) -> result`` over a wired Collective.
    Returns results indexed by rank; raises on any worker error."""
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)
    results = [None] * n
    errors = []

    def one(i):
        try:
            w = RabitWorker("127.0.0.1", tracker.port, jobid=str(i))
            rank = w.start(world_size=n if i == 0 else -1)
            c = Collective(w, io_timeout=io_timeout, **coll_kw)
            try:
                results[rank] = body(c, rank)
            finally:
                c.close(linger=0.2)
                w.shutdown()
        except Exception as e:  # noqa: BLE001 - surfaced via errors
            import traceback

            traceback.print_exc()
            errors.append((i, e))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    alive = [t for t in threads if t.is_alive()]
    try:
        assert not errors, errors
        assert not alive, f"{len(alive)} worker thread(s) wedged"
    finally:
        tracker.join()
        tracker.close()
    return results


def _inputs(n, dtype, size=37, signed=True):
    """Per-rank deterministic arrays; size 37 is coprime with every
    tested world size, so ring segments are uneven."""
    out = []
    rng = np.random.default_rng(1234)
    for r in range(n):
        a = rng.integers(-50 if signed else 0, 50, size)
        if np.issubdtype(np.dtype(dtype), np.floating):
            a = a + rng.random(size)
        out.append(np.asarray(a, dtype=dtype))
    return out


# -- reference oracle ---------------------------------------------------------


@pytest.mark.parametrize("n", [1, 2, 3, 4, 7, 8])
def test_reference_paths_agree_on_exact_ops(n):
    """Tree and ring fold in different orders; for integer sums and for
    min/max on any dtype the orders are exactly associative, so the two
    paths must agree (float sums may differ by rounding — documented)."""
    arrs = _inputs(n, np.int64)
    for op in ("sum", "max", "min"):
        t = reference_allreduce(arrs, op, "tree")
        r = reference_allreduce(arrs, op, "ring")
        assert np.array_equal(t, r), op
    f = _inputs(n, np.float64)
    for op in ("max", "min"):
        assert np.array_equal(
            reference_allreduce(f, op, "tree"),
            reference_allreduce(f, op, "ring"),
        )


def test_reference_rejects_unknown():
    with pytest.raises(Error):
        reference_allreduce([np.zeros(3)], "bogus")
    with pytest.raises(Error):
        reference_allreduce([np.zeros(3), np.zeros(3)], "sum", path="star")


# -- engine vs reference (the acceptance bit-identity matrix) -----------------


@pytest.mark.parametrize("n", [2, 3, 5, 8])
def test_allreduce_bit_identical_to_reference(n):
    """sum/max/min × tree/ring × float64/int64, 2-8 ranks: every rank's
    result equals the single-process reference bit for bit."""
    cases = [
        (op, path, dtype)
        for op in ("sum", "max", "min")
        for path in ("tree", "ring")
        for dtype in (np.float64, np.int64)
    ]
    inputs = {
        np.float64: _inputs(n, np.float64),
        np.int64: _inputs(n, np.int64),
    }

    def body(c, rank):
        return [
            c.allreduce(inputs[dtype][rank], op, path=path)
            for (op, path, dtype) in cases
        ]

    results = run_collective(n, body)
    for ci, (op, path, dtype) in enumerate(cases):
        ref = reference_allreduce(inputs[dtype], op, path)
        for rank in range(n):
            got = results[rank][ci]
            assert got.dtype == np.dtype(dtype)
            assert np.array_equal(got, ref), (op, path, dtype, rank)


def test_allreduce_f32_2d_and_custom_reducer():
    n = 3
    arrs = [
        np.arange(12, dtype=np.float32).reshape(3, 4) * (r + 1)
        for r in range(n)
    ]

    def body(c, rank):
        s = c.allreduce(arrs[rank], "sum", path="tree")
        # any elementwise f(acc, contrib) callable is a reducer
        p = c.allreduce(arrs[rank] + 1.0, np.multiply, path="tree")
        return s, p

    results = run_collective(n, body)
    ref_s = reference_allreduce(arrs, "sum", "tree")
    ref_p = reference_allreduce([a + 1.0 for a in arrs], np.multiply, "tree")
    for rank in range(n):
        s, p = results[rank]
        assert s.shape == (3, 4) and np.array_equal(s, ref_s)
        assert np.array_equal(p, ref_p)


def test_size_based_path_choice_and_telemetry():
    """path=None routes payloads >= ring_bytes over the ring; the
    tracker.collective.* counters tick."""
    n = 2
    big = [np.arange(4096, dtype=np.int64) + r for r in range(n)]
    small = [np.arange(4, dtype=np.int64) + r for r in range(n)]
    r0 = {k: v.value() for k, v in coll_mod._ROUNDS.items()}
    b0 = coll_mod._BYTES.value()

    def body(c, rank):
        return (
            c.allreduce(big[rank], "sum"),      # 32KB >= ring_bytes=1024
            c.allreduce(small[rank], "sum"),    # 32B  -> tree
        )

    results = run_collective(n, body, ring_bytes=1024)
    for rank in range(n):
        assert np.array_equal(
            results[rank][0], reference_allreduce(big, "sum", "ring")
        )
        assert np.array_equal(
            results[rank][1], reference_allreduce(small, "sum", "tree")
        )
    assert coll_mod._ROUNDS["ring"].value() - r0["ring"] == n
    assert coll_mod._ROUNDS["tree"].value() - r0["tree"] == n
    assert coll_mod._BYTES.value() > b0


@pytest.mark.parametrize("root", [0, 1, 2, 3])
def test_broadcast_from_any_root(root):
    n = 4
    payload = np.arange(19, dtype=np.float64) * 3.5 - 7

    def body(c, rank):
        buf = payload if rank == root else np.zeros_like(payload)
        return c.broadcast(buf, root=root)

    for rank, got in enumerate(run_collective(n, body)):
        assert np.array_equal(got, payload), (root, rank)


def test_barrier_orders_ranks():
    n = 3
    hits = []

    def body(c, rank):
        hits.append(("pre", rank))
        c.barrier()
        hits.append(("post", rank))
        return True

    assert all(run_collective(n, body))
    # every pre happens before any post could complete only if the
    # barrier is real: the first post entry must come after all n pres
    first_post = next(i for i, (k, _) in enumerate(hits) if k == "post")
    assert len([h for h in hits[:first_post] if h[0] == "pre"]) == n


def test_world_of_one_is_local():
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    w = RabitWorker("127.0.0.1", tracker.port, jobid="0")
    w.start(world_size=1)
    c = Collective(w)
    a = np.arange(5, dtype=np.float32)
    assert np.array_equal(c.allreduce(a, "sum"), a)
    assert np.array_equal(c.broadcast(a, root=0), a)
    c.barrier()
    assert c.load_checkpoint(timeout=0.5) == (0, None)
    with pytest.raises(Error):
        c.allreduce(a, "bogus")
    with pytest.raises(Error):
        c.broadcast(a, root=5)
    # close() with no live links returns immediately — it must not
    # busy-spin out the whole linger window throwing _LinkDied
    t0 = time.perf_counter()
    c2 = Collective(w)
    c2.close(linger=5.0)
    assert time.perf_counter() - t0 < 1.0, "close() spun the linger out"
    c.close()
    w.shutdown()
    tracker.join()
    tracker.close()


def test_oversized_payload_raises_checked_error(monkeypatch):
    """A payload over the frame limit fails LOUDLY at the sender: the
    receiver would reject the frame as corrupt and both sides would
    spin through recovery retrying the identical send forever."""
    from dmlc_core_tpu.tracker import collective as collective_mod

    eng = Collective.__new__(Collective)  # the check precedes any IO
    monkeypatch.setattr(collective_mod, "_MAX_PAYLOAD", 8)
    with pytest.raises(Error, match="frame limit"):
        eng._send_frame(0, collective_mod.K_DATA, 0, 0, b"x" * 9)


# -- fault tolerance ----------------------------------------------------------


def test_seeded_link_resets_heal_bit_identical(monkeypatch):
    """DMLC_COLLECTIVE_FAULTS resets: links are half-closed mid-job at
    seeded rounds; both endpoints run the reset-flood + re-rendezvous
    recovery and every round's result stays bit-identical."""
    monkeypatch.setenv("DMLC_COLLECTIVE_FAULTS", "resets=2,seed=7")
    n, rounds = 3, 12

    def inp(rank, r):
        return np.arange(8, dtype=np.float64) * (rank + 1) + r

    def body(c, rank):
        outs = [
            c.allreduce(inp(rank, r), "sum", path="tree")
            for r in range(rounds)
        ]
        return outs, c.recoveries

    results = run_collective(n, body)
    assert sum(rec for _, rec in results) > 0, "no reset ever fired"
    for r in range(rounds):
        ref = reference_allreduce([inp(k, r) for k in range(n)], "sum", "tree")
        for rank in range(n):
            assert np.array_equal(results[rank][0][r], ref), (rank, r)


def test_ring_round_faulted_by_reset_still_exact(monkeypatch):
    """A ring round a reset aborts retries over the tree; with int64
    payloads both fold orders are exact, so results must still equal
    the reference no matter which rounds faulted."""
    monkeypatch.setenv("DMLC_COLLECTIVE_FAULTS", "resets=2,seed=3")
    n, rounds = 3, 8
    arrs = [
        [np.arange(513, dtype=np.int64) * (k + 1) + r for k in range(n)]
        for r in range(rounds)
    ]

    def body(c, rank):
        return [
            c.allreduce(arrs[r][rank], "sum", path="ring")
            for r in range(rounds)
        ]

    results = run_collective(n, body, ring_bytes=64)
    for r in range(rounds):
        ref = reference_allreduce(arrs[r], "sum", "ring")
        for rank in range(n):
            assert np.array_equal(results[rank][r], ref), (rank, r)


def test_dead_worker_rejoins_bootstraps_and_replays():
    """The recovery story end to end, in-process: B dies mid-job, A
    discovers the dead link and re-enters the rendezvous; relaunched B'
    reclaims rank 1 via the jobid memo, pulls A's lazy checkpoint
    (params + version), fast-forwards its round clock, replays the
    missed rounds from A's result cache, and rejoins the live round —
    every result bit-identical to the reference."""
    n = 2
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)

    def inp(rank, r):
        return np.arange(8, dtype=np.float64) * (rank + 1) + r

    refs = [
        reference_allreduce([inp(k, r) for k in range(n)], "sum", "tree")
        for r in range(6)
    ]
    a_out, a_err = [], []

    def run_a():
        try:
            w = RabitWorker("127.0.0.1", tracker.port, jobid="0")
            rank = w.start(world_size=n)
            c = Collective(w, io_timeout=30)
            for r in range(6):
                a_out.append(c.allreduce(inp(rank, r), "sum", path="tree"))
                if r == 2:
                    c.checkpoint(b"state-after-round-2", version=3)
            c.close()
            w.shutdown()
        except Exception as e:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            a_err.append(e)

    b = RabitWorker("127.0.0.1", tracker.port, jobid="1")
    ta = threading.Thread(target=run_a)
    ta.start()
    rank_b = b.start(world_size=-1)
    cb = Collective(b, io_timeout=30)
    for r in range(5):
        assert np.array_equal(
            cb.allreduce(inp(rank_b, r), "sum", path="tree"), refs[r]
        )
    # B dies abruptly: links torn down, engine dropped mid-job
    b.close()
    time.sleep(0.2)

    w2 = RabitWorker("127.0.0.1", tracker.port, jobid="1")
    assert w2.start(world_size=-1) == rank_b  # jobid memo reclaims rank
    c2 = Collective(w2, io_timeout=30)
    version, state = c2.load_checkpoint()
    assert (version, state) == (3, b"state-after-round-2")
    assert c2.seq == 3  # fast-forwarded to the checkpoint's round
    for r in range(3, 6):  # rounds 3-4 replay from A's cache, 5 is live
        assert np.array_equal(
            c2.allreduce(inp(rank_b, r), "sum", path="tree"), refs[r]
        )
    c2.close()
    w2.shutdown()
    ta.join(60)
    assert not a_err
    tracker.join()
    tracker.close()


def test_wedged_peer_death_discovered_by_watch_push_not_timeout():
    """Instant peer-death notification: B wedges WITHOUT closing its
    sockets (no EOF for A to read), so only the supervisor-driven
    DeathWatch push can unblock A's round before the io_timeout
    backstop. A's timeout is set far beyond the test budget — if the
    push path were broken this test would fail on the join, not pass
    slowly."""
    n = 2
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)

    def inp(rank, r):
        return np.arange(6, dtype=np.float64) * (rank + 1) + r

    refs = [
        reference_allreduce([inp(k, r) for k in range(n)], "sum", "tree")
        for r in range(2)
    ]
    a_out, a_err = [], []
    a_recovered = threading.Event()

    def run_a():
        try:
            w = RabitWorker("127.0.0.1", tracker.port, jobid="0")
            rank = w.start(world_size=n)
            c = Collective(w, io_timeout=600)  # backstop way off-budget
            for r in range(2):
                a_out.append(c.allreduce(inp(rank, r), "sum", path="tree"))
            if c.recoveries:
                a_recovered.set()
            c.close()
            w.shutdown()
        except Exception as e:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            a_err.append(e)

    b = RabitWorker("127.0.0.1", tracker.port, jobid="1")
    ta = threading.Thread(target=run_a)
    ta.start()
    rank_b = b.start(world_size=-1)
    cb = Collective(b, io_timeout=30)
    assert np.array_equal(
        cb.allreduce(inp(rank_b, 0), "sum", path="tree"), refs[0]
    )
    # B wedges: stops participating, sockets stay open. A blocks in
    # round 1 until the tracker pushes the death notice.
    time.sleep(0.3)
    tracker.watch.note_task_rank("1", rank_b)
    tracker.watch.notify("1")  # what supervisor.on_task_failure does
    # relaunched B': the watch push made A's blocked recv fail NOW and
    # A is re-entering the rendezvous, waiting for this rejoin
    w2 = RabitWorker("127.0.0.1", tracker.port, jobid="1")
    assert w2.start(world_size=-1) == rank_b
    c2 = Collective(w2, io_timeout=30)
    c2.load_checkpoint(timeout=5)
    for r in range(c2.seq, 2):
        assert np.array_equal(
            c2.allreduce(inp(rank_b, r), "sum", path="tree"), refs[r]
        )
    c2.close()
    w2.shutdown()
    ta.join(90)
    assert not ta.is_alive(), "A never unblocked: watch push broken"
    assert not a_err
    assert a_recovered.is_set(), "A finished without a recovery?"
    for r in range(2):
        assert np.array_equal(a_out[r], refs[r])
    cb.close(linger=0.0)
    b.close()
    tracker.join()
    tracker.close()


# -- DeathWatch unit ----------------------------------------------------------


def _pipe_watcher():
    a, b = socket.socketpair()
    return FramedSocket(a), FramedSocket(b)


def test_deathwatch_fans_out_except_dead_rank():
    watch = DeathWatch()
    t0, w0 = _pipe_watcher()
    t1, w1 = _pipe_watcher()
    t2, w2 = _pipe_watcher()
    watch.add(0, t0)
    watch.add(1, t1)
    watch.add(2, t2)
    watch.note_task_rank("job-b", 1)
    watch.notify("job-b", host="h1")
    for fs in (w0, w2):
        fs.sock.settimeout(5)
        msg = json.loads(fs.recv_str())
        assert msg["dead_rank"] == 1 and msg["host"] == "h1"
    # the dead rank's own (stale) connection gets nothing: the frame
    # would arrive at its relaunched successor
    w1.sock.settimeout(0.2)
    with pytest.raises((socket.timeout, TimeoutError, ConnectionError)):
        w1.recv_str()
    assert watch.notices == 1
    watch.close()
    for fs in (w0, w1, w2):
        fs.close()


def test_deathwatch_unknown_task_falls_back_to_int_and_drops_dead():
    watch = DeathWatch()
    t0, w0 = _pipe_watcher()
    t1, w1 = _pipe_watcher()
    watch.add(0, t0)
    watch.add(1, t1)
    # watcher 0's connection is already dead: fan-out must drop it and
    # still reach watcher 1
    w0.close()
    t0.close()
    watch.notify(7)  # no task memo: task id IS the rank
    w1.sock.settimeout(5)
    assert json.loads(w1.recv_str())["dead_rank"] == 7
    assert 0 not in watch._watchers
    # re-registration replaces (relaunched worker's fresh connection)
    t1b, w1b = _pipe_watcher()
    watch.add(1, t1b)
    watch.notify(0)
    w1b.sock.settimeout(5)
    assert json.loads(w1b.recv_str())["dead_rank"] == 0
    watch.close()
    for fs in (w1, w1b):
        fs.close()


def test_notify_task_failure_is_noop_without_tracker():
    prev = active_watch()
    set_active_watch(None)
    try:
        notify_task_failure(3, "host")  # must not raise
    finally:
        set_active_watch(prev)


def test_chaos_spec_validation():
    from dmlc_core_tpu.tracker.collective import _PeerChaos

    with pytest.raises(Error):
        _PeerChaos("bogus_knob=1", 0)
    with pytest.raises(Error):
        _PeerChaos("resets=abc", 0)
    with pytest.raises(Error):
        _PeerChaos("kill_seq=1,kill_phase=middle", 0)
    c = _PeerChaos("resets=3,seed=5,kill_seq=2,kill_rank=1", 0)
    assert c.kill_rank == 1 and len(c.events) == 3
    # same spec + rank => same schedule; different rank => its own draw
    assert _PeerChaos("resets=3,seed=5", 0).events == _PeerChaos(
        "resets=3,seed=5", 0
    ).events


# -- the chaos drill ----------------------------------------------------------

DRILL_WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
from dmlc_core_tpu.tracker.client import RabitWorker
from dmlc_core_tpu.tracker.collective import Collective

w = RabitWorker()
rank = w.start()
world = w.world_size
c = Collective(w, io_timeout=60)

DIM, STEPS, SAVE_EVERY = 64, {steps}, 3
params = np.zeros(DIM)
step0 = 0
if int(os.environ.get("DMLC_NUM_ATTEMPT", "0")) > 0:
    version, state = c.load_checkpoint()
    if state:
        params = np.frombuffer(state, dtype=np.float64).copy()
        step0 = int(version)

rng = None
for s in range(step0, STEPS):
    # deterministic per-(rank, step) "gradient"
    g = np.sin(np.arange(DIM) * (rank + 1) + s).astype(np.float64)
    total = c.allreduce(g, "sum", path="tree")
    params = params - 0.01 * (total / world)
    if (s + 1) % SAVE_EVERY == 0:
        c.checkpoint(params.tobytes(), version=s + 1)

out = os.environ["DRILL_OUT"]
tmp = f"{{out}}.rank{{rank}}.tmp{{os.getpid()}}"
np.save(tmp + ".npy", params)
os.replace(tmp + ".npy", f"{{out}}.rank{{rank}}.npy")
c.close()
w.shutdown()
"""


def _run_drill(tmp_path, tag, steps, faults):
    """3-worker SGD job under a real Supervisor; returns per-rank final
    params. ``faults`` is the DMLC_COLLECTIVE_FAULTS spec ('' = clean)."""
    tracker = RabitTracker("127.0.0.1", 3)
    tracker.start(3)
    out = str(tmp_path / f"model_{tag}")
    script = tmp_path / f"drill_{tag}.py"
    script.write_text(DRILL_WORKER.format(repo=str(REPO), steps=steps))

    def launch(task_id, host, attempt):
        env = os.environ.copy()
        env.update({
            "DMLC_TRACKER_URI": "127.0.0.1",
            "DMLC_TRACKER_PORT": str(tracker.port),
            "DMLC_TASK_ID": str(task_id),
            "DMLC_NUM_ATTEMPT": str(attempt),
            "DRILL_OUT": out,
            "JAX_PLATFORMS": "cpu",
        })
        env.pop("DMLC_COLLECTIVE_FAULTS", None)
        if faults:
            env["DMLC_COLLECTIVE_FAULTS"] = faults
        return subprocess.Popen([sys.executable, str(script)], env=env)

    sup = Supervisor(
        launch, hosts=["localhost"], max_attempt=3,
        host_fail_limit=float("inf"), relaunch_backoff=0.1,
        on_task_failure=[
            # exactly what backends/local.py registers: reclaim +
            # instant peer-death notification, coexisting
            __import__(
                "dmlc_core_tpu.tracker.shardsvc", fromlist=["reclaim_task"]
            ).reclaim_task,
            notify_task_failure,
        ],
    )
    try:
        sup.run(3)
    finally:
        tracker.close()
    models = [np.load(f"{out}.rank{r}.npy") for r in range(3)]
    return models, sup


def test_chaos_drill_kill_mid_round_equals_clean_run(tmp_path):
    """THE acceptance drill: a 3-worker allreduce-SGD job, one worker
    SIGKILLed at the start of round 4 (a checkpoint exists at round 3,
    so the relaunch exercises true bootstrap-from-peer + replay), the
    supervisor relaunches it, the DeathWatch unblocks the survivors —
    final model BIT-IDENTICAL to the clean run, on every rank."""
    steps = 10
    clean, _ = _run_drill(tmp_path, "clean", steps, "")
    chaos, sup = _run_drill(
        tmp_path, "chaos", steps,
        "kill_seq=4,kill_rank=2,kill_phase=start",
    )
    assert sup.relaunches >= 1, "the kill never fired"
    for r in range(3):
        assert np.array_equal(clean[r], clean[0]), f"clean rank {r} differs"
        assert np.array_equal(chaos[r], chaos[0]), f"chaos rank {r} differs"
    assert np.array_equal(chaos[0], clean[0]), (
        "final model with injected kill+relaunch != clean run"
    )
