"""Fused libfm→ELL kernel parity: native/fastparse.cc dmlc_parse_libfm_ell
vs LibFMParser → FixedShapeBatcher('ell') composed (reference hot path
libfm_parser.h:67-144). The fused and generic batch streams must agree
bit-for-bit on labels/weights/indices/values/nnz/truncation across
dtypes, indexing modes, junk tokens, and sharding."""

import numpy as np
import pytest

from dmlc_core_tpu.data import create_parser, native
from dmlc_core_tpu.staging import BatchSpec, FixedShapeBatcher, ell_batches

fused = pytest.mark.skipif(
    not native.HAS_LIBFM_ELL, reason="native fused libfm kernel not built"
)


def _write_libfm(path, rows=400, k_max=6, one_based=False, seed=0,
                 junk=False):
    rng = np.random.default_rng(seed)
    lo = 1 if one_based else 0
    lines = []
    for i in range(rows):
        k = int(rng.integers(1, k_max + 1))
        toks = [f"{i % 2}" if i % 3 else f"{i % 2}:{0.5 + (i % 5)}"]
        for _ in range(k):
            fid = int(rng.integers(lo, 12))
            feat = int(rng.integers(lo, 500))
            if rng.random() < 0.5:
                toks.append(f"{fid}:{feat}:{rng.normal():.4f}")
            else:
                toks.append(f"{fid}:{feat}")
        if junk and i % 7 == 0:
            toks.append("noise")          # no colon: skipped
            toks.append("a:b:c")          # malformed numbers: skipped
            toks.append("3:4:5:6")        # extra colon: skipped
        lines.append(" ".join(toks))
    if junk:
        lines.insert(5, "not_a_label 1:2:3")  # bad label: line skipped
        lines.insert(9, "")                    # blank line
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def _spec(value_dtype="float32", B=64, K=4):
    return BatchSpec(
        batch_size=B, layout="ell", max_nnz=K,
        value_dtype=np.dtype(value_dtype),
    )


def _generic(path, spec, part_index=0, num_parts=1, indexing_mode=0):
    parser = create_parser(
        f"{path}?indexing_mode={indexing_mode}", part_index, num_parts,
        type="libfm", threaded=False,
    )
    batcher = FixedShapeBatcher(spec)
    out = list(batcher.batches(iter(parser)))
    parser.close()
    return out, batcher.truncated_nnz


def _fused(path, spec, part_index=0, num_parts=1, indexing_mode=0):
    from dmlc_core_tpu.staging import FusedEllLibFMBatches

    stream = FusedEllLibFMBatches(
        path, spec, part_index, num_parts, indexing_mode=indexing_mode
    )
    out = [
        type(b)(
            labels=b.labels.copy(), weights=b.weights.copy(),
            n_valid=b.n_valid, indices=b.indices.copy(),
            values=b.values.copy(), nnz=b.nnz.copy(),
        )
        for b in stream
    ]
    tr = stream.truncated_nnz
    stream.close()
    return out, tr


def _assert_equal(fb, gb):
    assert len(fb) == len(gb)
    for f, g in zip(fb, gb):
        assert f.n_valid == g.n_valid
        np.testing.assert_array_equal(f.labels, g.labels)
        np.testing.assert_array_equal(f.weights, g.weights)
        np.testing.assert_array_equal(f.nnz, g.nnz)
        np.testing.assert_array_equal(f.indices, g.indices)
        np.testing.assert_array_equal(f.values, g.values)


@fused
@pytest.mark.parametrize("value_dtype", ["float32", "float16"])
def test_fused_matches_generic(tmp_path, value_dtype):
    path = _write_libfm(str(tmp_path / "d.libfm"), rows=500, k_max=7)
    f, ft = _fused(path, _spec(value_dtype))
    g, gt = _generic(path, _spec(value_dtype))
    _assert_equal(f, g)
    assert ft == gt and ft > 0  # k_max 7 > K=4 → truncation exercised


@fused
def test_fused_matches_generic_with_junk_tokens(tmp_path):
    path = _write_libfm(str(tmp_path / "j.libfm"), rows=300, junk=True)
    f, ft = _fused(path, _spec())
    g, gt = _generic(path, _spec())
    _assert_equal(f, g)
    assert ft == gt


@fused
def test_one_based_indexing_modes(tmp_path):
    path = _write_libfm(str(tmp_path / "o.libfm"), rows=200, one_based=True)
    f, _ = _fused(path, _spec(), indexing_mode=1)
    g, _ = _generic(path, _spec(), indexing_mode=1)
    _assert_equal(f, g)
    # auto mode resolves 1-based from the head probe = explicit mode 1
    a, _ = _fused(path, _spec(), indexing_mode=-1)
    _assert_equal(a, f)
    # 1-based data under mode 1 never produces feature id -1: wrapped ids
    # are zeroed + counted, never negative
    assert all(int(b.indices.min()) >= 0 for b in f)


@fused
def test_sharded_exact_cover(tmp_path):
    path = _write_libfm(str(tmp_path / "s.libfm"), rows=400)
    labels = []
    for part in range(3):
        batches, _ = _fused(path, _spec(B=32), part_index=part, num_parts=3)
        for b in batches:
            labels.extend(b.labels[: b.n_valid].tolist())
    assert len(labels) == 400
    full, _ = _generic(path, _spec(B=400))
    np.testing.assert_array_equal(
        np.sort(np.asarray(labels)), np.sort(full[0].labels[:400])
    )


@fused
def test_dispatcher_routes_libfm(tmp_path):
    from dmlc_core_tpu.staging import FusedEllLibFMBatches
    from dmlc_core_tpu.staging.fused import _GenericBatchStream

    path = _write_libfm(str(tmp_path / "r.libfm"), rows=50)
    s = ell_batches(path + "?format=libfm", _spec())
    assert isinstance(s, FusedEllLibFMBatches)
    total = sum(int(b.n_valid) for b in s)
    s.close()
    assert total == 50
    # non-fusable spec falls back to the generic path, same totals
    g = ell_batches(
        path + "?format=libfm",
        BatchSpec(batch_size=64, layout="ell", max_nnz=4,
                  index_dtype=np.dtype(np.int64)),
    )
    assert isinstance(g, _GenericBatchStream)
    assert sum(int(b.n_valid) for b in g) == 50
    g.close()


@fused
def test_threaded_fan_out_covers(tmp_path):
    path = _write_libfm(str(tmp_path / "t.libfm"), rows=300)
    s = ell_batches(path + "?format=libfm", _spec(B=32), nthread=2)
    labels = [x for b in s for x in b.labels[: b.n_valid].tolist()]
    s.close()
    assert len(labels) == 300


@fused
def test_dispatcher_indexing_mode_kwarg(tmp_path):
    """ell_batches(indexing_mode=1) matches the URI sugar on both the
    fused path and the generic fallback (dense_batches API symmetry)."""
    path = _write_libfm(str(tmp_path / "k.libfm"), rows=60, one_based=True)

    def indices(**kw):
        s = ell_batches(path + "?format=libfm", _spec(), **kw)
        out = [b.indices.copy() for b in s]
        s.close()
        return np.concatenate(out)

    via_kwarg = indices(indexing_mode=1)
    s2 = ell_batches(path + "?format=libfm&indexing_mode=1", _spec())
    via_uri = np.concatenate([b.indices.copy() for b in s2])
    s2.close()
    np.testing.assert_array_equal(via_kwarg, via_uri)


def test_auto_probe_negative_ids_resolve_zero_based(tmp_path):
    """Negative ids in the head must resolve auto mode to 0-based (the
    native CSR rule is min of BOTH fields and features > 0), not shift
    every column by one."""
    from dmlc_core_tpu.staging.fused import _probe_libfm_base

    assert _probe_libfm_base(b"1 2:-3:1.0 4:7:2.0\n") == 0
    assert _probe_libfm_base(b"1 2:3:1.0 -4:7:2.0\n") == 0
    assert _probe_libfm_base(b"1 2:3:1.0 4:7:2.0\n") == 1
    assert _probe_libfm_base(b"1 0:3:1.0\n") == 0


@fused
def test_fuzz_parity(tmp_path):
    """Randomized noisy libfm text stages identically through the fused
    kernel and the generic path (the ELL analogue of
    tests/test_native.py::test_fuzz_parity; runs under ASan via make
    check — TSan is not relevant here: each fused producer owns its
    buffers, threads never share a ring slot)."""
    rng = np.random.default_rng(23)
    junk_pool = ["x", "a:b", "1:2:3:4", ":", "::", "-:-", "7:", ":9",
                 "1:2:nan", "1e3:4", "  "]
    for trial in range(12):
        lines = []
        for _ in range(60):
            toks = []
            r = rng.random()
            if r < 0.15:
                toks.append("junklabel")  # line dropped by both paths
            elif r < 0.4:
                toks.append(f"{rng.normal():.4g}:{abs(rng.normal()):.3g}")
            else:
                toks.append(f"{rng.normal():.4g}")
            for _ in range(int(rng.integers(0, 9))):
                if rng.random() < 0.25:
                    toks.append(str(rng.choice(junk_pool)))
                else:
                    fid = int(rng.integers(-2, 15))
                    feat = int(rng.integers(-2, 3000))
                    if rng.random() < 0.5:
                        toks.append(f"{fid}:{feat}:{rng.normal():.5g}")
                    else:
                        toks.append(f"{fid}:{feat}")
            lines.append(" ".join(toks))
        eol = "\r\n" if trial % 3 == 0 else "\n"
        path = str(tmp_path / f"fz{trial}.libfm")
        with open(path, "w", newline="") as f:
            f.write(eol.join(lines) + eol)
        for dtype in ("float32", "float16"):
            f_b, f_t = _fused(path, _spec(dtype, B=37, K=4))
            g_b, g_t = _generic(path, _spec(dtype, B=37, K=4))
            _assert_equal(f_b, g_b)
            assert f_t == g_t, (trial, dtype)


def test_generic_fallback_without_native(tmp_path, monkeypatch):
    """ell_batches format=libfm works (same totals) when the kernel is
    reported missing."""
    path = _write_libfm(str(tmp_path / "f.libfm"), rows=80)
    monkeypatch.setattr(native, "HAS_LIBFM_ELL", False)
    s = ell_batches(path + "?format=libfm", _spec())
    assert sum(int(b.n_valid) for b in s) == 80
    s.close()
