"""Fused native parse→dense-batch kernel: parity with the generic path.

The fused kernel (native/fastparse.cc dmlc_parse_libsvm_dense +
staging/fused.py) must produce byte-identical batches to
LibSVMParser → FixedShapeBatcher('dense') composed, across formats'
edge cases. Skipped wholesale when the native kernel isn't built.
"""


import numpy as np
import pytest

from dmlc_core_tpu.data import create_parser, native
from dmlc_core_tpu.staging import (
    BatchSpec,
    FixedShapeBatcher,
    FusedDenseLibSVMBatches,
    dense_batches,
)

pytestmark = pytest.mark.skipif(
    not native.HAS_DENSE, reason="native fused kernel not built"
)

EDGE_CASES = b"""\
1 0:1.5 3:-2.25 7:0.125
0 1:3 2:4.75
# full comment line
1:0.5 2:1.25 4:-1
-1 qid:7 0:2.5 5:1.75

1 0:0.0000001 7:123456.75
0 3:1e2 5:-1E-2
1 2:inf 4:-inf
1 0:99 1:1.23456789012345678
binarylabel 0:1 1:2
1 3 5 7
0 2:1.5 2:2.5 2:-0.5
1 0:1.5 junk 3:2.5 4:bad:1 5:x
1 100:5.0 3:1.0
0 0:+1.5 1:-0.0
"""

ONE_BASED = b"""\
3.5 1:0.5 4:1.5
-2 2:2.5 3:-1.25
1 1:1 2:1
"""


def _generic(data_path, spec, **parser_kw):
    parser = create_parser(
        data_path, type="libsvm", threaded=False, **parser_kw
    )
    out = list(FixedShapeBatcher(spec).batches(iter(parser)))
    parser.close()
    return out


def _fused(data_path, spec, **kw):
    stream = FusedDenseLibSVMBatches(data_path, spec, ring=64, **kw)
    out = list(stream)
    stream.close()
    return out


def _assert_batches_equal(fused, generic):
    assert len(fused) == len(generic)
    for i, (f, g) in enumerate(zip(fused, generic)):
        assert f.n_valid == g.n_valid, f"batch {i} n_valid"
        np.testing.assert_array_equal(f.labels, g.labels, err_msg=f"batch {i}")
        np.testing.assert_array_equal(f.weights, g.weights, err_msg=f"batch {i}")
        np.testing.assert_array_equal(f.x, g.x, err_msg=f"batch {i} x")


@pytest.mark.parametrize("dtype", ["float32", "float16"])
@pytest.mark.parametrize("payload", [EDGE_CASES, ONE_BASED])
def test_parity_edge_cases(tmp_path, dtype, payload):
    p = tmp_path / "edge.libsvm"
    p.write_bytes(payload)
    spec = BatchSpec(
        batch_size=4,
        layout="dense",
        num_features=8,
        value_dtype=np.dtype(dtype),
    )
    _assert_batches_equal(_fused(str(p), spec), _generic(str(p), spec))


def test_parity_bom_and_tail(tmp_path):
    p = tmp_path / "bom.libsvm"
    p.write_bytes(b"\xef\xbb\xbf1 0:1.5\n0 1:2.5\n1 2:3.5")  # BOM + NOEOL
    spec = BatchSpec(batch_size=2, layout="dense", num_features=4)
    fused = _fused(str(p), spec)
    _assert_batches_equal(fused, _generic(str(p), spec))
    assert fused[-1].n_valid == 1  # padded tail batch
    assert fused[-1].weights[1] == 0.0


def test_parity_crlf(tmp_path):
    p = tmp_path / "crlf.libsvm"
    p.write_bytes(b"1 0:1.5\r\n0 1:2.5\r1 2:3.5\n")
    spec = BatchSpec(batch_size=4, layout="dense", num_features=4)
    _assert_batches_equal(_fused(str(p), spec), _generic(str(p), spec))


def test_parity_random_many_batches(tmp_path):
    rng = np.random.default_rng(7)
    n, d = 5000, 13
    lines = []
    for i in range(n):
        feats = " ".join(
            f"{j}:{rng.normal():.7f}"
            for j in range(d)
            if rng.random() < 0.7
        )
        lines.append(f"{int(rng.integers(0, 2))} {feats}\n")
    p = tmp_path / "rand.libsvm"
    p.write_text("".join(lines))
    for dtype in ("float32", "float16"):
        spec = BatchSpec(
            batch_size=256,
            layout="dense",
            num_features=d,
            value_dtype=np.dtype(dtype),
        )
        _assert_batches_equal(_fused(str(p), spec), _generic(str(p), spec))


def test_sharded_parts_cover_all_rows(tmp_path):
    n = 1000
    p = tmp_path / "shard.libsvm"
    p.write_text("".join(f"{i % 2} 0:{i}.5 1:1.0\n" for i in range(n)))
    spec = BatchSpec(batch_size=64, layout="dense", num_features=2)
    seen = []
    for part in range(3):
        stream = FusedDenseLibSVMBatches(
            str(p), spec, part_index=part, num_parts=3
        )
        for b in stream:
            seen.extend(np.asarray(b.x[: b.n_valid, 0], np.float64).tolist())
        stream.close()
    # every row lands exactly once across the 3 parts
    assert sorted(seen) == [i + 0.5 for i in range(n)]


def test_overflow_error_policy(tmp_path):
    p = tmp_path / "over.libsvm"
    p.write_text("1 0:1.0 99:2.0\n")
    spec = BatchSpec(
        batch_size=2, layout="dense", num_features=4, overflow="error"
    )
    from dmlc_core_tpu.utils.logging import Error

    with pytest.raises(Error):
        _fused(str(p), spec)
    # truncate (default) drops and counts
    spec2 = BatchSpec(batch_size=2, layout="dense", num_features=4)
    stream = FusedDenseLibSVMBatches(str(p), spec2)
    list(stream)
    assert stream.truncated_nnz == 1
    stream.close()


@pytest.mark.jax
def test_ring_reuse_through_staging_pipeline(tmp_path):
    """Staged device batches must not alias ring buffers: after the ring
    wraps many times, device contents still match a fresh parse."""
    jax = pytest.importorskip("jax")
    from dmlc_core_tpu.staging import StagingPipeline

    n = 2000
    p = tmp_path / "ring.libsvm"
    p.write_text("".join(f"1 0:{i}.25 1:-{i}.5\n" for i in range(n)))
    spec = BatchSpec(batch_size=32, layout="dense", num_features=2)
    stream = FusedDenseLibSVMBatches(str(p), spec)  # default ring
    pipe = StagingPipeline(stream, depth=2)
    staged = [np.asarray(dev["x"]) for dev in pipe]
    pipe.close()
    stream.close()
    expect = list(_fused(str(p), spec))
    assert len(staged) == len(expect)
    for got, want in zip(staged, expect):
        np.testing.assert_array_equal(got, want.x)


def test_dense_batches_factory_matches_fused(tmp_path):
    p = tmp_path / "f.libsvm"
    p.write_text("1 0:1.5 2:2.5\n0 1:3.5\n")
    spec = BatchSpec(batch_size=2, layout="dense", num_features=4)
    stream = dense_batches(str(p), spec)
    assert isinstance(stream, FusedDenseLibSVMBatches)
    out = list(stream)
    stream.close()
    _assert_batches_equal(out, _generic(str(p), spec))


def test_dense_batches_fallback_forwards_indexing_mode(tmp_path, monkeypatch):
    """Without the native kernel, dense_batches must still honor
    indexing_mode (and expose close())."""
    p = tmp_path / "onebased.libsvm"
    p.write_text("1 1:0.5 4:1.5\n0 2:2.5\n")
    spec = BatchSpec(batch_size=2, layout="dense", num_features=4)
    fused_out = _fused(str(p), spec, indexing_mode=1)
    monkeypatch.setattr(native, "HAS_DENSE", False)
    fallback = dense_batches(str(p), spec, indexing_mode=1)
    assert not isinstance(fallback, FusedDenseLibSVMBatches)
    out = list(fallback)
    fallback.close()  # closes the underlying parser (no thread/fd leak)
    _assert_batches_equal(fused_out, out)
    # URI-carried form reaches the fused path too
    monkeypatch.setattr(native, "HAS_DENSE", True)
    via_uri = FusedDenseLibSVMBatches(f"{p}?indexing_mode=1", spec, ring=64)
    out_uri = list(via_uri)
    via_uri.close()
    _assert_batches_equal(out_uri, out)


def test_fused_via_input_split_uri(tmp_path):
    """Globby/multi-file URIs take the InputSplit source, same results."""
    a = tmp_path / "a.libsvm"
    b = tmp_path / "b.libsvm"
    a.write_text("1 0:1.5\n0 1:2.5\n")
    b.write_text("1 2:3.5\n")
    uri = f"{a};{b}"
    spec = BatchSpec(batch_size=2, layout="dense", num_features=4)
    stream = FusedDenseLibSVMBatches(uri, spec)
    out = list(stream)
    stream.close()
    got = np.concatenate([x.x[: x.n_valid] for x in out])
    assert got.shape[0] == 3
    assert got[0, 0] == 1.5 and got[1, 1] == 2.5 and got[2, 2] == 3.5


# -- fused csv → dense --------------------------------------------------------

csv_fused = pytest.mark.skipif(
    not native.HAS_CSV_DENSE, reason="native fused csv kernel not built"
)


def _generic_csv(data_path, spec, **parser_kw):
    parser = create_parser(data_path, type="csv", threaded=False, **parser_kw)
    out = list(FixedShapeBatcher(spec).batches(iter(parser)))
    parser.close()
    return out


def _fused_csv(data_path, spec, **kw):
    from dmlc_core_tpu.staging import FusedDenseCSVBatches

    stream = FusedDenseCSVBatches(data_path, spec, ring=64, **kw)
    out = list(stream)
    stream.close()
    return out


@csv_fused
@pytest.mark.parametrize("dtype", ["float32", "float16"])
def test_csv_parity_random(tmp_path, dtype):
    rng = np.random.default_rng(11)
    n, d = 3000, 14
    lines = []
    for i in range(n):
        row = [f"{rng.normal():.6f}" for _ in range(d)]
        row[0] = str(int(rng.integers(0, 2)))  # label column 0
        lines.append(",".join(row) + "\n")
    p = tmp_path / "rand.csv"
    p.write_text("".join(lines))
    uri = str(p) + "?label_column=0"
    spec = lambda: BatchSpec(
        batch_size=128, layout="dense", num_features=d - 1,
        value_dtype=np.dtype(dtype),
    )
    _assert_batches_equal(_fused_csv(uri, spec()), _generic_csv(uri, spec()))


@csv_fused
def test_csv_parity_weight_column_and_uri_args(tmp_path):
    p = tmp_path / "w.csv"
    p.write_text("1.0;0.5;2.5;3.5\n0.0;2.0;4.5;5.5\n1.0;1.0;6.0;7.0\n")
    uri = str(p) + "?delimiter=;&label_column=0&weight_column=1"
    spec = lambda: BatchSpec(batch_size=2, layout="dense", num_features=2)
    fused = _fused_csv(uri, spec())
    generic = _generic_csv(uri, spec())
    _assert_batches_equal(fused, generic)
    assert fused[0].weights[0] == 0.5  # weight column honored


@csv_fused
def test_csv_parity_junk_cells_and_crlf(tmp_path):
    p = tmp_path / "junk.csv"
    # longest-prefix float semantics: junk -> 0.0, "1.5x" -> 1.5
    p.write_bytes(b"1,junk,2.5\r\n0,1.5x,-3\r1,.5,1e2\n\n0,+2,0x1\n")
    uri = str(p) + "?label_column=0"
    spec = lambda: BatchSpec(batch_size=3, layout="dense", num_features=2)
    _assert_batches_equal(_fused_csv(uri, spec()), _generic_csv(uri, spec()))


@csv_fused
def test_csv_truncation_counts(tmp_path):
    p = tmp_path / "wide.csv"
    p.write_text("".join(f"1,{i},2,3,4\n" for i in range(10)))
    from dmlc_core_tpu.staging import FusedDenseCSVBatches

    spec = BatchSpec(batch_size=4, layout="dense", num_features=2)
    stream = FusedDenseCSVBatches(str(p) + "?label_column=0", spec, ring=8)
    list(stream)
    assert stream.truncated_nnz == 20  # 2 overflow columns x 10 rows
    stream.close()


@csv_fused
def test_csv_bad_line_raises(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("1,2,3\nno-delimiter-here\n")
    from dmlc_core_tpu.staging import FusedDenseCSVBatches
    from dmlc_core_tpu.utils.logging import Error

    spec = BatchSpec(batch_size=4, layout="dense", num_features=2)
    with pytest.raises(Error, match="Delimiter"):
        # with a label column, the delimiter-less line yields no feature
        # cells, which the generic parser treats as a malformed file
        list(FusedDenseCSVBatches(str(p) + "?label_column=0", spec))


@csv_fused
def test_dense_batches_dispatches_csv(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2,3\n0,4,5\n")
    from dmlc_core_tpu.staging import FusedDenseCSVBatches, dense_batches

    spec = BatchSpec(batch_size=2, layout="dense", num_features=2)
    stream = dense_batches(str(p) + "?format=csv&label_column=0", spec)
    assert isinstance(stream, FusedDenseCSVBatches)
    batches = list(stream)
    stream.close()
    np.testing.assert_array_equal(batches[0].labels, [1.0, 0.0])
    np.testing.assert_array_equal(batches[0].x, [[2, 3], [4, 5]])


# -- threaded fan-out (ShardedFusedBatches) -----------------------------------

def _collect_rows(stream):
    """(labels multiset, total valid rows, per-row x) copied out of ring."""
    rows = []
    for b in stream:
        for i in range(b.n_valid):
            rows.append((float(b.labels[i]), tuple(np.asarray(b.x[i]))))
    return rows


def test_sharded_fused_libsvm_exact_cover(tmp_path):
    rng = np.random.default_rng(13)
    n, d = 2000, 6
    lines = [
        f"{i} " + " ".join(f"{j}:{rng.normal():.5f}" for j in range(d)) + "\n"
        for i in range(n)
    ]
    p = tmp_path / "t.libsvm"
    p.write_text("".join(lines))
    from dmlc_core_tpu.staging import ShardedFusedBatches, dense_batches

    spec = lambda: BatchSpec(batch_size=128, layout="dense", num_features=d)
    single = _collect_rows(dense_batches(str(p), spec()))
    sharded_stream = dense_batches(str(p), spec(), nthread=3)
    assert isinstance(sharded_stream, ShardedFusedBatches)
    sharded = _collect_rows(sharded_stream)
    sharded_stream.close()
    # same rows, order interleaved across sub-shards
    assert sorted(single) == sorted(sharded)
    assert sharded_stream.rows_out == n


def _ell_rows(stream):
    """Full per-row ELL payload copied out of the ring (order-free)."""
    rows = []
    for b in stream:
        for i in range(b.n_valid):
            rows.append((
                float(b.labels[i]), float(b.weights[i]), int(b.nnz[i]),
                tuple(np.asarray(b.indices[i])),
                tuple(np.asarray(b.values[i]).astype(np.float32)),
            ))
    return rows


@pytest.mark.parametrize("nthread", [2, 4])
@pytest.mark.parametrize(
    "fmt", ["libsvm_dense", "csv_dense", "rowrec", "libsvm_ell", "libfm_ell"]
)
def test_nthread_equivalence_all_paths(tmp_path, fmt, nthread):
    """VERDICT r3 #8 gate: every fused path's global output is IDENTICAL
    (full row payloads + truncation counters, as a multiset) for
    nthread ∈ {1, 2, 4}. The bench host has 1 vCPU, so the fan-out's
    perf is unverifiable there — this pins that engaging it can never
    change results, only speed."""
    from dmlc_core_tpu.staging import dense_batches, ell_batches

    rng = np.random.default_rng(100)
    n = 1500
    if fmt == "libsvm_dense":
        d = 7
        p = tmp_path / "a.libsvm"
        p.write_text("".join(
            f"{i % 2} " + " ".join(
                f"{j}:{rng.normal():.5f}" for j in range(d)
            ) + "\n"
            for i in range(n)
        ))
        make = lambda nt: dense_batches(
            str(p),
            BatchSpec(batch_size=128, layout="dense", num_features=d),
            nthread=nt,
        )
        collect = _collect_rows
    elif fmt == "csv_dense":
        d = 5
        p = tmp_path / "a.csv"
        p.write_text("".join(
            f"{i % 2}," + ",".join(
                f"{rng.normal():.5f}" for _ in range(d)
            ) + "\n"
            for i in range(n)
        ))
        make = lambda nt: dense_batches(
            str(p) + "?format=csv&label_column=0",
            BatchSpec(batch_size=128, layout="dense", num_features=d),
            nthread=nt,
        )
        collect = _collect_rows
    else:
        k = 5
        if fmt == "rowrec":
            from dmlc_core_tpu.data.row_block import RowBlock
            from dmlc_core_tpu.data.rowrec import write_rowrec
            from dmlc_core_tpu.io.stream import FileStream

            blk = RowBlock(
                offset=np.arange(n + 1, dtype=np.int64) * k,
                label=np.arange(n, dtype=np.float32),
                index=rng.integers(0, 999, n * k).astype(np.uint32),
                value=rng.normal(size=n * k).astype(np.float32),
            )
            p = tmp_path / "a.rec"
            with FileStream(str(p), "w") as f:
                write_rowrec(f, [blk])
            uri = str(p)
        elif fmt == "libsvm_ell":
            p = tmp_path / "a.svm"
            p.write_text("".join(
                f"{i % 2} " + " ".join(
                    f"{int(rng.integers(0, 5000))}:{rng.normal():.4f}"
                    for _ in range(int(rng.integers(1, 8)))
                ) + "\n"
                for i in range(n)
            ))
            uri = str(p) + "?format=libsvm"
        else:
            p = tmp_path / "a.libfm"
            p.write_text("".join(
                f"{i % 2} " + " ".join(
                    f"{int(rng.integers(0, 9))}:"
                    f"{int(rng.integers(0, 5000))}:{rng.normal():.4f}"
                    for _ in range(int(rng.integers(1, 8)))
                ) + "\n"
                for i in range(n)
            ))
            uri = str(p) + "?format=libfm"
        make = lambda nt: ell_batches(
            uri, BatchSpec(batch_size=128, layout="ell", max_nnz=k),
            nthread=nt,
        )
        collect = _ell_rows

    base_stream = make(None)
    base = collect(base_stream)
    base_trunc = base_stream.truncated_nnz
    base_stream.close()
    s = make(nthread)
    got = collect(s)
    trunc = s.truncated_nnz
    s.close()
    assert sorted(got) == sorted(base), (fmt, nthread)
    assert trunc == base_trunc, (fmt, nthread)


def test_rowrec_corrupt_frame_fails_fast(tmp_path):
    """A bad-magic frame mid-shard must raise immediately (corrupt), not
    accumulate the remaining shard as a 'partial record' until
    end-of-split (ADVICE r3). A trailing truncation stays a truncation
    error."""
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import write_rowrec
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.staging import ell_batches
    from dmlc_core_tpu.utils.logging import Error as DmlcError

    rng = np.random.default_rng(5)
    n, k = 200, 3
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n, dtype=np.float32),
        index=rng.integers(0, 99, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "c.rec")
    with FileStream(rec, "w") as f:
        write_rowrec(f, [blk])
    data = open(rec, "rb").read()
    frame = 8 + 12 + k * 8
    # clobber the magic of a mid-file frame
    bad = bytearray(data)
    bad[frame * 50: frame * 50 + 4] = b"\xde\xad\xbe\xef"
    corrupt_path = tmp_path / "corrupt.rec"
    corrupt_path.write_bytes(bytes(bad))
    # force the non-mmap path (the carry-accumulation path ADVICE flagged)
    spec = BatchSpec(batch_size=64, layout="ell", max_nnz=k)
    s = ell_batches(str(corrupt_path) + "?shuffle_parts=1", spec)
    # 'bad magic' only: the OLD end-of-split message ('truncated or
    # corrupt ... trailing bytes') must NOT satisfy this test — the point
    # is the immediate raise, not the late diagnosis
    with pytest.raises(DmlcError, match="bad magic"):
        for _ in s:
            pass
    s.close()


def test_fused_rowrec_rejects_cachefile(tmp_path):
    """#cachefile is silently dropped by the fused rowrec path's URI
    forwarding — it must be refused loudly (ADVICE r3)."""
    from dmlc_core_tpu.staging import FusedEllRowRecBatches
    from dmlc_core_tpu.utils.logging import Error as DmlcError

    with pytest.raises(DmlcError, match="cachefile"):
        FusedEllRowRecBatches(
            str(tmp_path / "x.rec") + "#" + str(tmp_path / "cache"),
            BatchSpec(batch_size=8, layout="ell", max_nnz=2),
        )


def test_indexed_writer_requires_byte0(tmp_path):
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream, MemoryStream
    from dmlc_core_tpu.utils.logging import Error as DmlcError

    p = str(tmp_path / "a.rec")
    with open(p, "wb") as f:
        f.write(b"prefix")
    data = FileStream(p, "a")
    with pytest.raises(DmlcError, match="byte 0"):
        IndexedRecordIOWriter(data, MemoryStream())
    data.close()


def test_probe_cache_invalidated_on_rewrite(tmp_path):
    """Auto indexing-base probes are cached by (uri, mtime, size): a file
    rewritten at the same path must re-probe (ADVICE r3)."""
    import time as time_mod

    from dmlc_core_tpu.staging.fused import _probe_base_from_uri

    p = tmp_path / "p.libsvm"
    p.write_text("1 1:0.5 2:0.5\n")  # 1-based heuristic
    assert _probe_base_from_uri(str(p)) == 1
    time_mod.sleep(0.01)
    p.write_text("1 0:0.5 2:0.75\n")  # id 0 appears → 0-based, new size
    assert _probe_base_from_uri(str(p)) == 0


@pytest.mark.jax
def test_sharded_fused_rowrec_through_pipeline(tmp_path):
    """Threaded ELL fan-out through the staging pipeline: every label
    lands exactly once on device."""
    jax = pytest.importorskip("jax")
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import write_rowrec
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.staging import StagingPipeline, ell_batches

    rng = np.random.default_rng(14)
    n, k = 1000, 5
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=np.arange(n, dtype=np.float32),
        index=rng.integers(0, 100, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "t.rec")
    with FileStream(rec, "w") as f:
        write_rowrec(f, [blk])
    spec = BatchSpec(batch_size=64, layout="ell", max_nnz=k)
    stream = ell_batches(rec, spec, nthread=2)
    pipe = StagingPipeline(stream)
    got = []
    for dev in pipe:
        labels = np.asarray(dev["labels"])
        weights = np.asarray(dev["weights"])
        got.append(labels[weights > 0])  # padding rows carry weight 0
    stream.close()
    pipe.close()
    all_labels = np.concatenate(got)
    np.testing.assert_array_equal(np.sort(all_labels), np.arange(n))
