"""Native C++ parse core: availability + exact parity with the Python
fallbacks (the semantic contract stated in native/fastparse.cc)."""

import subprocess
import sys

import numpy as np
import pytest

from dmlc_core_tpu.data import native
from dmlc_core_tpu.data.csv_parser import CSVParser
from dmlc_core_tpu.data.libfm_parser import LibFMParser
from dmlc_core_tpu.data.libsvm_parser import LibSVMParser
from dmlc_core_tpu.io.split import LineSplitter

pytestmark = pytest.mark.skipif(
    not native.load(), reason="native library not built"
)


def make_parser(cls, tmp_path, args=None):
    p = tmp_path / "stub.txt"
    p.write_text("0 0:0\n" if cls is not CSVParser else "0\n")
    src = LineSplitter(str(p), 0, 1)
    return cls(src, args or {}, nthread=1)


def both_ways(parser, data: bytes):
    native_blk = parser.parse_block(data)
    py_blk = parser._parse_block_py(data)
    return native_blk, py_blk


def assert_blocks_equal(a, b):
    np.testing.assert_array_equal(a.offset, b.offset)
    np.testing.assert_allclose(a.label, b.label, rtol=1e-6)
    np.testing.assert_array_equal(a.index, b.index)
    for name in ("value", "weight"):
        av, bv = getattr(a, name), getattr(b, name)
        assert (av is None) == (bv is None), f"{name} presence differs"
        if av is not None:
            np.testing.assert_allclose(av, bv, rtol=1e-6)
    for name in ("qid", "field"):
        av, bv = getattr(a, name), getattr(b, name)
        assert (av is None) == (bv is None), f"{name} presence differs"
        if av is not None:
            np.testing.assert_array_equal(av, bv)


LIBSVM_CASES = [
    b"",
    b"1 0:1.5 3:2.5\n-1 1:0.5\n",
    b"1 0:1.5 3:2.5 # comment\n# full comment\n\n0.5:2.0 qid:7 2:1.0\n",
    b"1 3 5 9\n0 2 4\n",                      # binary features
    b"1 1:0.5 3:2\n0 2:1\n",                  # ints as values
    b"1 qid:abc 1:0.5\n",                     # malformed qid
    b"1 qid: 1:0.5\n",                        # empty qid
    b"abc 1:0.5\n1 0:2.0\n",                  # non-numeric label line skipped
    b"1 x:0.5 2:bad 3:1.0\n",                 # malformed feature tokens
    b"1 0:1e-3 2:1E4 3:-2.5e+2\n",            # exponents
    b"1:0.25 0:1\n",                          # weighted, no qid
    b"1 0:inf 1:nan\n",                       # special floats
    b"NA 1:1\n2 2:2",                          # NOEOL last line
    b"1 0:1.5\r\n2 1:2.5\r0 2:0.5\n",         # CR / CRLF
    b"1 0:1\x0b2:3\n1\x0c0:1\n",               # \v \f are separators
    b"1 99999999999999999999:1 1:2\n",       # index > int64: token skipped
    b"1 0:1_0 2:3\n1_0 0:1\n",               # PEP-515 underscores rejected
    b"1 0:1e999 1:1e-999\n",                  # float over/underflow
    b"1 qid:99999999999999999999 0:1\n",      # qid overflow -> 0
]


@pytest.mark.parametrize("case", range(len(LIBSVM_CASES)))
@pytest.mark.parametrize("mode", [0, 1, -1])
def test_libsvm_parity(tmp_path, case, mode):
    parser = make_parser(LibSVMParser, tmp_path, {"indexing_mode": mode})
    a, b = both_ways(parser, LIBSVM_CASES[case])
    assert_blocks_equal(a, b)


CSV_CASES = [
    b"",
    b"1.0,2.0,3.0\n4.0,5.0,6.0\n",
    b"1.0,,3.0\n",                # empty cell -> 0
    b"1,abc,3\n",                 # junk cell -> 0
    b"7.0,1.0,0.25\n",
    b"1\n2\n3\n",                 # single column
    b"1.5e3,2E-2\n",
    b"-1.0,+2.0\n",
    b"9,8,7",                     # NOEOL
    b"1_0,2\n",                   # underscores: prefix parse
    b"1e999,2\n",                 # overflow -> inf
]


@pytest.mark.parametrize("case", range(len(CSV_CASES)))
@pytest.mark.parametrize(
    "args",
    [{}, {"label_column": 0}, {"label_column": 0, "weight_column": 2}],
)
def test_csv_parity(tmp_path, case, args):
    parser = make_parser(CSVParser, tmp_path, args)
    data = CSV_CASES[case]
    if data == b"1\n2\n3\n" and args.get("label_column") == 0:
        return  # single column entirely consumed by the label: no feature
    a, b = both_ways(parser, data)
    assert_blocks_equal(a, b)


def test_csv_error_parity(tmp_path):
    # the lone cell is consumed by the label -> no feature -> error
    parser = make_parser(CSVParser, tmp_path, {"label_column": 0})
    with pytest.raises(Exception, match="Delimiter"):
        parser._parse_block_py(b"1\n")
    with pytest.raises(Exception, match="Delimiter"):
        parser.parse_block(b"1\n")


LIBFM_CASES = [
    b"",
    b"1 0:3:1.5 2:7:0.5\n-1:0.5 1:4:2.0\n",
    b"1 1:1:0.5 2:3:0.5\n",
    b"1 0:3 2:7\n",               # field:index without value
    b"1 junk 0:3:1.5 5\n",        # malformed tokens skipped
    b"x 0:3:1.5\n1 1:1:1\n",      # bad label line skipped
]


@pytest.mark.parametrize("case", range(len(LIBFM_CASES)))
@pytest.mark.parametrize("mode", [0, 1, -1])
def test_libfm_parity(tmp_path, case, mode):
    parser = make_parser(LibFMParser, tmp_path, {"indexing_mode": mode})
    a, b = both_ways(parser, LIBFM_CASES[case])
    assert_blocks_equal(a, b)


def test_fuzz_parity(tmp_path):
    """Randomized libsvm blocks parse identically both ways."""
    rng = np.random.default_rng(7)
    parser = make_parser(LibSVMParser, tmp_path, {"indexing_mode": -1})
    for trial in range(20):
        lines = []
        for _ in range(50):
            n = rng.integers(0, 8)
            feats = " ".join(
                f"{int(j)}:{rng.normal():.6g}"
                for j in sorted(rng.integers(0, 1000, n))
            )
            label = f"{rng.normal():.4g}"
            if rng.random() < 0.3:
                label += f":{abs(rng.normal()):.3g}"
            if rng.random() < 0.3:
                feats = f"qid:{rng.integers(0, 99)} " + feats
            lines.append(f"{label} {feats}\n")
        data = "".join(lines).encode()
        a, b = both_ways(parser, data)
        assert_blocks_equal(a, b)


def test_no_native_fallback_env(tmp_path):
    """DMLC_TPU_NO_NATIVE=1 disables the fast path cleanly."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import sys; sys.path.insert(0, %r); "
        "from dmlc_core_tpu.data import native; "
        "assert not native.AVAILABLE" % repo
    )
    subprocess.run(
        [sys.executable, "-c", code],
        check=True,
        env={"DMLC_TPU_NO_NATIVE": "1", "PATH": "/usr/bin:/bin"},
    )
