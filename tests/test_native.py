"""Native C++ parse core: availability + exact parity with the Python
fallbacks (the semantic contract stated in native/fastparse.cc)."""

import subprocess
import sys

import numpy as np
import pytest

from dmlc_core_tpu.data import native
from dmlc_core_tpu.data.csv_parser import CSVParser
from dmlc_core_tpu.data.libfm_parser import LibFMParser
from dmlc_core_tpu.data.libsvm_parser import LibSVMParser
from dmlc_core_tpu.io.split import LineSplitter

pytestmark = pytest.mark.skipif(
    not native.load(), reason="native library not built"
)


def make_parser(cls, tmp_path, args=None):
    p = tmp_path / "stub.txt"
    p.write_text("0 0:0\n" if cls is not CSVParser else "0\n")
    src = LineSplitter(str(p), 0, 1)
    return cls(src, args or {}, nthread=1)


def both_ways(parser, data: bytes):
    native_blk = parser.parse_block(data)
    py_blk = parser._parse_block_py(data)
    return native_blk, py_blk


def assert_blocks_equal(a, b):
    np.testing.assert_array_equal(a.offset, b.offset)
    np.testing.assert_allclose(a.label, b.label, rtol=1e-6)
    np.testing.assert_array_equal(a.index, b.index)
    for name in ("value", "weight"):
        av, bv = getattr(a, name), getattr(b, name)
        assert (av is None) == (bv is None), f"{name} presence differs"
        if av is not None:
            np.testing.assert_allclose(av, bv, rtol=1e-6)
    for name in ("qid", "field"):
        av, bv = getattr(a, name), getattr(b, name)
        assert (av is None) == (bv is None), f"{name} presence differs"
        if av is not None:
            np.testing.assert_array_equal(av, bv)


LIBSVM_CASES = [
    b"",
    b"1 0:1.5 3:2.5\n-1 1:0.5\n",
    b"1 0:1.5 3:2.5 # comment\n# full comment\n\n0.5:2.0 qid:7 2:1.0\n",
    b"1 3 5 9\n0 2 4\n",                      # binary features
    b"1 1:0.5 3:2\n0 2:1\n",                  # ints as values
    b"1 qid:abc 1:0.5\n",                     # malformed qid
    b"1 qid: 1:0.5\n",                        # empty qid
    b"abc 1:0.5\n1 0:2.0\n",                  # non-numeric label line skipped
    b"1 x:0.5 2:bad 3:1.0\n",                 # malformed feature tokens
    b"1 0:1e-3 2:1E4 3:-2.5e+2\n",            # exponents
    b"1:0.25 0:1\n",                          # weighted, no qid
    b"1 0:inf 1:nan\n",                       # special floats
    b"NA 1:1\n2 2:2",                          # NOEOL last line
    b"1 0:1.5\r\n2 1:2.5\r0 2:0.5\n",         # CR / CRLF
    b"1 0:1\x0b2:3\n1\x0c0:1\n",               # \v \f are separators
    b"1 99999999999999999999:1 1:2\n",       # index > int64: token skipped
    b"1 0:1_0 2:3\n1_0 0:1\n",               # PEP-515 underscores rejected
    b"1 0:1e999 1:1e-999\n",                  # float over/underflow
    b"1 qid:99999999999999999999 0:1\n",      # qid overflow -> 0
]


@pytest.mark.parametrize("case", range(len(LIBSVM_CASES)))
@pytest.mark.parametrize("mode", [0, 1, -1])
def test_libsvm_parity(tmp_path, case, mode):
    parser = make_parser(LibSVMParser, tmp_path, {"indexing_mode": mode})
    a, b = both_ways(parser, LIBSVM_CASES[case])
    assert_blocks_equal(a, b)


CSV_CASES = [
    b"",
    b"1.0,2.0,3.0\n4.0,5.0,6.0\n",
    b"1.0,,3.0\n",                # empty cell -> 0
    b"1,abc,3\n",                 # junk cell -> 0
    b"7.0,1.0,0.25\n",
    b"1\n2\n3\n",                 # single column
    b"1.5e3,2E-2\n",
    b"-1.0,+2.0\n",
    b"9,8,7",                     # NOEOL
    b"1_0,2\n",                   # underscores: prefix parse
    b"1e999,2\n",                 # overflow -> inf
]


@pytest.mark.parametrize("case", range(len(CSV_CASES)))
@pytest.mark.parametrize(
    "args",
    [{}, {"label_column": 0}, {"label_column": 0, "weight_column": 2}],
)
def test_csv_parity(tmp_path, case, args):
    parser = make_parser(CSVParser, tmp_path, args)
    data = CSV_CASES[case]
    if data == b"1\n2\n3\n" and args.get("label_column") == 0:
        return  # single column entirely consumed by the label: no feature
    a, b = both_ways(parser, data)
    assert_blocks_equal(a, b)


def test_csv_error_parity(tmp_path):
    # the lone cell is consumed by the label -> no feature -> error
    parser = make_parser(CSVParser, tmp_path, {"label_column": 0})
    with pytest.raises(Exception, match="Delimiter"):
        parser._parse_block_py(b"1\n")
    with pytest.raises(Exception, match="Delimiter"):
        parser.parse_block(b"1\n")


LIBFM_CASES = [
    b"",
    b"1 0:3:1.5 2:7:0.5\n-1:0.5 1:4:2.0\n",
    b"1 1:1:0.5 2:3:0.5\n",
    b"1 0:3 2:7\n",               # field:index without value
    b"1 junk 0:3:1.5 5\n",        # malformed tokens skipped
    b"x 0:3:1.5\n1 1:1:1\n",      # bad label line skipped
]


@pytest.mark.parametrize("case", range(len(LIBFM_CASES)))
@pytest.mark.parametrize("mode", [0, 1, -1])
def test_libfm_parity(tmp_path, case, mode):
    parser = make_parser(LibFMParser, tmp_path, {"indexing_mode": mode})
    a, b = both_ways(parser, LIBFM_CASES[case])
    assert_blocks_equal(a, b)


def test_fuzz_parity(tmp_path):
    """Randomized libsvm blocks parse identically both ways."""
    rng = np.random.default_rng(7)
    parser = make_parser(LibSVMParser, tmp_path, {"indexing_mode": -1})
    for trial in range(20):
        lines = []
        for _ in range(50):
            n = rng.integers(0, 8)
            feats = " ".join(
                f"{int(j)}:{rng.normal():.6g}"
                for j in sorted(rng.integers(0, 1000, n))
            )
            label = f"{rng.normal():.4g}"
            if rng.random() < 0.3:
                label += f":{abs(rng.normal()):.3g}"
            if rng.random() < 0.3:
                feats = f"qid:{rng.integers(0, 99)} " + feats
            lines.append(f"{label} {feats}\n")
        data = "".join(lines).encode()
        a, b = both_ways(parser, data)
        assert_blocks_equal(a, b)


def test_no_native_fallback_env(tmp_path):
    """DMLC_TPU_NO_NATIVE=1 disables the fast path cleanly."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import sys; sys.path.insert(0, %r); "
        "from dmlc_core_tpu.data import native; "
        "assert not native.AVAILABLE" % repo
    )
    subprocess.run(
        [sys.executable, "-c", code],
        check=True,
        env={"DMLC_TPU_NO_NATIVE": "1", "PATH": "/usr/bin:/bin"},
    )


def test_shuffle_mt19937_parity_with_random_random():
    """The native Fisher-Yates must replay random.Random.shuffle
    BIT-IDENTICALLY (same MT draws, same rejection loop, same swaps) —
    the shuffled-read permutation contract hangs on it."""
    import random

    if not native.HAS_SHUFFLE:
        pytest.skip("shuffle kernel not loaded")
    for seed in (0, 1, 7, 111, 10**9):
        for n in (0, 1, 2, 3, 9, 17, 255, 256, 257, 1024, 9999):
            ref = list(range(n))
            random.Random(seed).shuffle(ref)
            perm = np.arange(n, dtype=np.int64)
            assert native.shuffle_mt19937(random.Random(seed), perm)
            assert perm.tolist() == ref, (seed, n)
    # the empty permutation is a no-op, not a refusal
    assert native.shuffle_mt19937(
        random.Random(1), np.empty(0, dtype=np.int64)
    )
    # oversize permutations REFUSE (CPython's getrandbits consumes
    # multiple MT words per call beyond 2^31, which the kernel does not
    # mirror — silent order divergence if this guard rots). A
    # zero-stride view fakes the length without 16 GB of memory; the
    # size check must fire before anything touches the buffer.
    big = np.lib.stride_tricks.as_strided(
        np.zeros(1, dtype=np.int64), shape=(1 << 31,), strides=(0,)
    )
    assert not native.shuffle_mt19937(random.Random(1), big)


def test_rowrec_gather_kernel_matches_sequential_kernel():
    """The gather entry point must decode the same records the
    sequential chunk kernel does — including multi-part chains,
    truncated-feature counting, and bad-payload skipping."""
    import struct

    if not (native.HAS_ELL and native.HAS_GATHER_ELL):
        pytest.skip("ELL kernels not loaded")
    from dmlc_core_tpu.io.recordio import RecordIOWriter
    from dmlc_core_tpu.io.stream import MemoryStream

    rng = np.random.default_rng(4)
    KMAGIC = 0xCED7230A
    payloads = []
    for i in range(40):
        n = int(rng.integers(0, 6))
        idx = rng.integers(0, 1000, n).astype("<u4")
        if i == 7:
            idx = idx.copy()
            if n:
                idx[0] = 0x80000001  # unfit id: zeroed + truncated
        val = rng.normal(size=n).astype("<f4")
        payloads.append(
            struct.pack("<ffI", float(i), 1.0, n)
            + idx.tobytes() + val.tobytes()
        )
    # one payload containing the magic word at an aligned offset → the
    # writer emits a multi-part chain
    payloads.append(
        struct.pack("<ffI", 99.0, 1.0, 2)
        + struct.pack("<II", KMAGIC, 5)
        + np.ones(2, "<f4").tobytes()
    )
    ms = MemoryStream()
    w = RecordIOWriter(ms)
    starts = []
    for p in payloads:
        starts.append(ms.tell())
        w.write_record(p)
    data = np.frombuffer(ms.getvalue(), dtype=np.uint8)
    st = np.asarray(starts, dtype=np.int64)
    sz = np.diff(np.r_[st, len(data)]).astype(np.int64)
    B, K = len(payloads) + 3, 4

    def alloc():
        return (
            np.zeros((B, K), np.int32),
            np.zeros((B, K), np.float32),
            np.zeros(B, np.int32),
            np.zeros(B, np.float32),
            np.zeros(B, np.float32),
        )

    seq = alloc()
    r1 = native.parse_rowrec_ell(data.tobytes(), 0, *seq, 0)
    gat = alloc()
    r2 = native.parse_rowrec_gather_ell(data, st, sz, 0, len(st), *gat, 0)
    assert r1[0] == r2[0] == len(payloads)  # rows written
    assert r1[2] == r2[2] > 0  # truncated (unfit id + beyond-K)
    assert r1[3] == r2[3] == 0
    assert r1[4] == r2[4] == 0
    for a, b in zip(seq, gat):
        np.testing.assert_array_equal(a, b)
    # permuted slices decode in slice order
    perm = rng.permutation(len(st))
    gat2 = alloc()
    native.parse_rowrec_gather_ell(
        data, st[perm].copy(), sz[perm].copy(), 0, len(st), *gat2, 0
    )
    np.testing.assert_array_equal(
        gat2[3][: len(st)], seq[3][: len(st)][perm]
    )
