"""Tests for the InputSplit family.

Ports the reference regression suite in spirit: unittest_inputsplit.cc
(NOEOL handling :39-90, distributed shard counts :116-145, recordio decode
:159-190) plus shuffle/cache/threaded wrappers (SURVEY §4).
"""

import os
import struct

import pytest

from dmlc_core_tpu.io import (
    CachedInputSplit,
    IndexedRecordIOSplitter,
    InputSplitShuffle,
    LineSplitter,
    MemoryStream,
    RecordIOSplitter,
    RecordIOWriter,
    TemporaryDirectory,
    ThreadedInputSplit,
    create_input_split,
)
from dmlc_core_tpu.utils import Error


def write_files(tmp, spec):
    """spec: {name: bytes}"""
    paths = []
    for name, data in spec.items():
        path = os.path.join(tmp, name)
        with open(path, "wb") as f:
            f.write(data)
        paths.append(path)
    return paths


def all_records(split):
    out = []
    while True:
        rec = split.next_record()
        if rec is None:
            return out
        out.append(bytes(rec))


# -- text splits -------------------------------------------------------------
def test_line_split_single_file():
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"l1\nl2\nl3\n"})
        s = LineSplitter(p, 0, 1)
        assert all_records(s) == [b"l1", b"l2", b"l3"]
        s.before_first()
        assert all_records(s) == [b"l1", b"l2", b"l3"]


def test_line_split_noeol_last_line():
    # reference unittest_inputsplit.cc:39-66 — file without trailing newline
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"l1\nl2\nl3"})
        assert all_records(LineSplitter(p, 0, 1)) == [b"l1", b"l2", b"l3"]


def test_line_split_noeol_multifile_join():
    # reference PR#385: NOEOL file joined with next file must not merge lines
    with TemporaryDirectory() as tmp:
        write_files(tmp.path, {"a.txt": b"a1\na2", "b.txt": b"b1\nb2\n"})
        uri = f"{tmp.path}/a.txt;{tmp.path}/b.txt"
        assert all_records(LineSplitter(uri, 0, 1)) == [b"a1", b"a2", b"b1", b"b2"]


def test_line_split_crlf_and_blank_lines():
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"x\r\n\r\ny\rz\n\n"})
        assert all_records(LineSplitter(p, 0, 1)) == [b"x", b"y", b"z"]


def test_line_split_distributed_no_loss_no_dup():
    # reference test_split_libsvm_distributed (unittest_inputsplit.cc:116-145):
    # 5 files read as N parts — every record exactly once
    lines = [f"line-{i:03d}".encode() for i in range(37)]
    with TemporaryDirectory() as tmp:
        spec = {}
        k = 0
        for fi in range(5):
            cnt = [7, 9, 3, 11, 7][fi]
            body = b"\n".join(lines[k : k + cnt])
            if fi % 2 == 0:
                body += b"\n"  # mix NOEOL and EOL files
            spec[f"part{fi}.txt"] = body
            k += cnt
        write_files(tmp.path, spec)
        uri = ";".join(os.path.join(tmp.path, f"part{fi}.txt") for fi in range(5))
        for nsplit in (1, 2, 3, 5, 8):
            got = []
            for rank in range(nsplit):
                got.extend(all_records(LineSplitter(uri, rank, nsplit)))
            assert sorted(got) == sorted(lines), f"nsplit={nsplit}"


def test_line_split_directory_uri():
    with TemporaryDirectory() as tmp:
        write_files(tmp.path, {"a.txt": b"1\n", "b.txt": b"2\n"})
        assert sorted(all_records(LineSplitter(tmp.path, 0, 1))) == [b"1", b"2"]


def test_line_split_regex_uri():
    with TemporaryDirectory() as tmp:
        write_files(
            tmp.path, {"d0.txt": b"a\n", "d1.txt": b"b\n", "other.csv": b"c\n"}
        )
        s = LineSplitter(os.path.join(tmp.path, r"d.\.txt"), 0, 1)
        assert sorted(all_records(s)) == [b"a", b"b"]


def test_split_missing_file_errors():
    with pytest.raises(Error, match="Cannot find any files"):
        LineSplitter("/definitely/not/here.txt", 0, 1)


# -- recordio splits ---------------------------------------------------------
def make_rec_file(path, records):
    with open(path, "wb") as f:
        pass
    ms = MemoryStream()
    w = RecordIOWriter(ms)
    offsets = []
    for r in records:
        offsets.append(ms.tell())
        w.write_record(r)
    with open(path, "wb") as f:
        f.write(ms.getvalue())
    return offsets


def test_recordio_split_roundtrip_sharded():
    magic = struct.pack("<I", 0xCED7230A)
    records = [f"rec{i}".encode() * (i % 9 + 1) for i in range(41)]
    records += [magic * 2, b"ab" + magic + b"cd"]
    with TemporaryDirectory() as tmp:
        p = os.path.join(tmp.path, "data.rec")
        make_rec_file(p, records)
        for nsplit in (1, 2, 3, 7):
            got = []
            for rank in range(nsplit):
                got.extend(all_records(RecordIOSplitter(p, rank, nsplit)))
            assert got == records, f"nsplit={nsplit}"  # order preserved


def test_recordio_split_multifile():
    recs_a = [f"a{i}".encode() for i in range(10)]
    recs_b = [f"b{i}".encode() for i in range(10)]
    with TemporaryDirectory() as tmp:
        pa, pb = os.path.join(tmp.path, "a.rec"), os.path.join(tmp.path, "b.rec")
        make_rec_file(pa, recs_a)
        make_rec_file(pb, recs_b)
        got = []
        for rank in range(2):
            got.extend(all_records(RecordIOSplitter(f"{pa};{pb}", rank, 2)))
        assert got == recs_a + recs_b


# -- indexed recordio --------------------------------------------------------
def make_indexed_rec(tmp, records):
    p = os.path.join(tmp, "data.rec")
    offsets = make_rec_file(p, records)
    idx = os.path.join(tmp, "data.idx")
    with open(idx, "w") as f:
        for i, off in enumerate(offsets):
            f.write(f"{i} {off}\n")
    return p, idx


def test_indexed_recordio_sequential():
    records = [f"idx{i}".encode() * (i % 4 + 1) for i in range(23)]
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(p, idx, 0, 1, batch_size=5)
        assert all_records(s) == records
        # count-based sharding: parts get ceil-division record counts
        got = []
        for rank in range(4):
            part = all_records(IndexedRecordIOSplitter(p, idx, rank, 4, batch_size=5))
            got.extend(part)
        assert got == records


def test_indexed_recordio_shuffle_permutes_and_covers():
    records = [f"srec{i:02d}".encode() for i in range(31)]
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(p, idx, 0, 1, batch_size=4, shuffle=True, seed=7)
        epoch1 = all_records(s)
        s.before_first()
        epoch2 = all_records(s)
        assert sorted(epoch1) == sorted(records)  # full coverage
        assert sorted(epoch2) == sorted(records)
        assert epoch1 != records  # actually shuffled
        assert epoch1 != epoch2  # reshuffled per epoch (reference :221-233)
        # determinism: same seed → same sequence
        s2 = IndexedRecordIOSplitter(p, idx, 0, 1, batch_size=4, shuffle=True, seed=7)
        assert all_records(s2) == epoch1


def test_indexed_recordio_batch_shuffle_coalesced():
    """shuffle='batch': spans of batch_size contiguous records permuted,
    one coalesced read per span — full coverage, span-internal order
    preserved, reshuffled per epoch, sharding exact."""
    records = [f"brec{i:02d}".encode() for i in range(37)]
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(
            p, idx, 0, 1, batch_size=5, shuffle="batch", seed=9
        )
        epoch1 = all_records(s)
        s.before_first()
        epoch2 = all_records(s)
        assert sorted(epoch1) == sorted(records)  # full coverage
        assert sorted(epoch2) == sorted(records)
        assert epoch1 != records  # span order permuted
        assert epoch1 != epoch2  # reshuffled per epoch
        # span-internal order preserved: every aligned 5-record span of
        # the original appears contiguously
        spans = [records[i:i + 5] for i in range(0, len(records), 5)]
        for span in spans:
            i = epoch1.index(span[0])
            assert epoch1[i:i + len(span)] == span
        # sharding stays exact under batch shuffle
        got = []
        for rank in range(3):
            got.extend(
                all_records(
                    IndexedRecordIOSplitter(
                        p, idx, rank, 3, batch_size=5, shuffle="batch"
                    )
                )
            )
        assert sorted(got) == sorted(records)
        # URI sugar routes the mode
        from dmlc_core_tpu.io import split as io_split

        sp = io_split.create(
            f"{p}?index={idx}&shuffle=batch&batch_size=5",
            type="recordio", threaded=False,
        )
        assert isinstance(sp, IndexedRecordIOSplitter)
        assert sp.shuffle_mode == "batch"


# -- wrappers ----------------------------------------------------------------
def test_threaded_input_split_prefetch():
    lines = [f"t{i}".encode() for i in range(100)]
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"\n".join(lines) + b"\n"})
        s = ThreadedInputSplit(LineSplitter(p, 0, 1))
        assert all_records(s) == lines
        s.before_first()
        assert all_records(s) == lines
        s.close()


def test_cached_input_split_replays():
    lines = [f"c{i}".encode() for i in range(50)]
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"\n".join(lines) + b"\n"})
        cache = os.path.join(tmp.path, "cache.bin")
        s = CachedInputSplit(ThreadedInputSplit(LineSplitter(p, 0, 1)), cache)
        assert all_records(s) == lines  # first epoch builds cache
        assert os.path.exists(cache)
        os.unlink(p)  # prove epoch 2 reads the cache, not the source
        s.before_first()
        assert all_records(s) == lines
        s.close()


def test_input_split_shuffle_macro():
    lines = [f"m{i:03d}".encode() for i in range(64)]
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"\n".join(lines) + b"\n"})
        base = LineSplitter(p, 0, 1)
        s = InputSplitShuffle(base, 0, 1, num_shuffle_parts=8, seed=3)
        epoch1 = all_records(s)
        s.before_first()
        epoch2 = all_records(s)
        assert sorted(epoch1) == sorted(lines)
        assert sorted(epoch2) == sorted(lines)
        assert epoch1 != lines  # sub-part order shuffled
        assert epoch1 != epoch2


def test_create_factory_with_cache_sugar():
    lines = [f"f{i}".encode() for i in range(20)]
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"\n".join(lines) + b"\n"})
        cache = os.path.join(tmp.path, "cc")
        s = create_input_split(f"{p}#{cache}", 0, 1, "text")
        assert isinstance(s, CachedInputSplit)
        assert all_records(s) == lines
        assert os.path.exists(f"{cache}")
        s.close()
        s2 = create_input_split(p, 0, 1, "text")
        assert isinstance(s2, ThreadedInputSplit)
        assert all_records(s2) == lines
        s2.close()
        with pytest.raises(Error, match="unknown InputSplit type"):
            create_input_split(p, 0, 1, "parquet")
        with pytest.raises(Error, match="index_uri"):
            create_input_split(p, 0, 1, "indexed_recordio")


def test_reset_partition_to_empty_clears_state():
    # regression: stale chunk iterator must not serve the old partition
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"l1\nl2\nl3\nl4\n"})
        s = LineSplitter(p, 0, 1)
        assert s.next_record() == b"l1"
        s.reset_partition(5, 6)  # empty byte range
        assert s.next_record() is None
    records = [f"z{i}".encode() for i in range(10)]
    with TemporaryDirectory() as tmp:
        p, idx = make_indexed_rec(tmp.path, records)
        s = IndexedRecordIOSplitter(p, idx, 0, 1, batch_size=3, shuffle=True)
        assert s.next_record() is not None
        s.reset_partition(7, 8)  # 7*2 >= 10 → empty rank
        assert s.next_record() is None


def test_threaded_split_keeps_capacity_across_reset():
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"a\nb\nc\n"})
        s = ThreadedInputSplit(LineSplitter(p, 0, 1), max_capacity=8)
        s.reset_partition(0, 1)
        assert s._iter._cap == 8
        assert all_records(s) == [b"a", b"b", b"c"]
        s.close()


def test_create_shuffle_with_cache_rejected():
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"a\nb\n"})
        with pytest.raises(Error, match="freeze"):
            create_input_split(f"{p}#cache", 0, 1, "text", num_shuffle_parts=2)


def test_total_size_and_empty_partition():
    with TemporaryDirectory() as tmp:
        (p,) = write_files(tmp.path, {"a.txt": b"ab\ncd\n"})
        s = LineSplitter(p, 0, 1)
        assert s.total_size() == 6
        # more parts than bytes: high ranks get empty partitions
        s8 = LineSplitter(p, 7, 8)
        assert all_records(s8) == []
