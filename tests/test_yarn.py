"""YARN backend: RM REST submission path + the Python in-container AM.

REST client/context against a mock ResourceManager (same mock-server
technique as tests/test_cloudfs.py's WebHDFS coverage — reference has no
REST path, its client is Java: tracker/yarn/src/.../Client.java); the AM
tier proves tracker/yarn_am.py carries the Java AM's relaunch semantics
(ApplicationMaster.java:537-569) for in-container tasks."""

import json
import os
import subprocess
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dmlc_core_tpu.tracker import opts as tracker_opts
from dmlc_core_tpu.tracker.backends.yarn import (
    YarnRestClient,
    build_rest_context,
    submit_via_rest,
)
from dmlc_core_tpu.tracker.yarn_am import task_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class MockRM:
    """Threaded mock of the RM 'Cluster Applications API' endpoints the
    backend uses; records every submission context it accepts."""

    def __init__(self, states=("ACCEPTED", "RUNNING", "FINISHED")):
        self.submitted = []
        self.killed = []
        self._states = list(states)
        self._polls = 0
        mock = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path == "/ws/v1/cluster/apps/new-application":
                    self._json(200, {
                        "application-id": "application_1_0001",
                        "maximum-resource-capability": {
                            "memory": 8192, "vCores": 4,
                        },
                    })
                elif self.path == "/ws/v1/cluster/apps":
                    n = int(self.headers["Content-Length"])
                    mock.submitted.append(json.loads(self.rfile.read(n)))
                    self._json(202, {})
                else:
                    self._json(404, {"error": self.path})

            def do_GET(self):
                if self.path.endswith("/state"):
                    i = min(mock._polls, len(mock._states) - 1)
                    mock._polls += 1
                    if mock._states[i] == "ERR":  # scripted RM blip
                        self._json(503, {"error": "rm restarting"})
                        return
                    self._json(200, {"state": mock._states[i]})
                elif "/ws/v1/cluster/apps/" in self.path:
                    self._json(200, {"app": {
                        "state": mock._states[-1],
                        "finalStatus": "SUCCEEDED",
                    }})
                else:
                    self._json(404, {"error": self.path})

            def do_PUT(self):
                if self.path.endswith("/state"):
                    n = int(self.headers["Content-Length"])
                    mock.killed.append(json.loads(self.rfile.read(n)))
                    self._json(200, {"state": "KILLED"})
                else:
                    self._json(404, {"error": self.path})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def mock_rm():
    rm = MockRM()
    yield rm
    rm.close()


def _args(extra=()):
    return tracker_opts.get_opts(
        ["--cluster", "yarn", "--num-workers", "2", "--num-servers", "1",
         "--worker-memory", "1g", "--server-memory", "512m",
         "--worker-cores", "1", "--server-cores", "1", *extra, "true"]
    )


def test_rest_client_roundtrip(mock_rm):
    c = YarnRestClient(mock_rm.url)
    fresh = c.new_application()
    assert fresh["application-id"] == "application_1_0001"
    assert fresh["maximum-resource-capability"]["memory"] == 8192
    c.submit_application({"application-id": fresh["application-id"]})
    assert mock_rm.submitted[0]["application-id"] == "application_1_0001"
    assert c.state("application_1_0001") == "ACCEPTED"
    assert c.report("application_1_0001")["finalStatus"] == "SUCCEEDED"
    c.kill("application_1_0001")
    assert mock_rm.killed == [{"state": "KILLED"}]


def test_rest_client_errors_name_the_endpoint(mock_rm):
    c = YarnRestClient(mock_rm.url)
    with pytest.raises(RuntimeError, match="HTTP 404"):
        c._request("POST", "/ws/v1/cluster/nope")
    dead = YarnRestClient("http://127.0.0.1:1")
    with pytest.raises(RuntimeError, match="unreachable"):
        dead.new_application()


def test_rest_context_contract():
    """Submission context carries the DMLC env, the AM command wrapping
    the user command, job-wide resources clamped to cluster caps, and
    the DMLC_MAX_ATTEMPT relaunch budget."""
    args = _args()
    envs = {"DMLC_TRACKER_URI": "10.0.0.5", "DMLC_TRACKER_PORT": 9091,
            "DMLC_NUM_WORKER": 2, "DMLC_NUM_SERVER": 1}
    ctx = build_rest_context(
        args, "application_1_0001", envs,
        max_caps={"memory": 2100, "vCores": 4},
    )
    assert ctx["application-id"] == "application_1_0001"
    assert ctx["application-type"] == "DMLC-TPU"
    assert ctx["queue"] == "default"
    assert ctx["max-app-attempts"] == 3
    # 2*1024 + 512 = 2560 clamped to the 2100 cap; 3 vCores under the 4 cap
    assert ctx["resource"] == {"memory": 2100, "vCores": 3}
    cmd = ctx["am-container-spec"]["commands"]["command"]
    assert "-m dmlc_core_tpu.tracker.yarn_am true" in cmd
    assert "<LOG_DIR>" in cmd
    env = {
        e["key"]: e["value"]
        for e in ctx["am-container-spec"]["environment"]["entry"]
    }
    assert env["DMLC_TRACKER_URI"] == "10.0.0.5"
    assert env["DMLC_NUM_WORKER"] == "2"
    assert env["DMLC_JOB_CLUSTER"] == "yarn"
    assert env["DMLC_MAX_ATTEMPT"] == "3"


def test_rest_submit_end_to_end(mock_rm):
    """submit_via_rest drives new-application → submit → poll on the
    mock RM against a REAL tracker rendezvous. No worker ever connects
    here, so the app FINISHing successfully must abort the join with a
    clear error (anti-wedge) rather than hanging forever."""
    args = _args()
    args.num_servers = 0  # rabit branch polls abort_check
    with pytest.raises(RuntimeError, match="never completed"):
        submit_via_rest(args, mock_rm.url, poll_interval=0.01)
    ctx = mock_rm.submitted[0]
    assert ctx["application-id"] == "application_1_0001"
    # caps from new-application were applied
    assert ctx["resource"]["memory"] <= 8192


def test_rest_submit_failed_app_aborts_join():
    rm = MockRM(states=("ACCEPTED", "FAILED"))
    try:
        args = _args()
        args.num_servers = 0
        with pytest.raises(RuntimeError, match="FAILED"):
            submit_via_rest(args, rm.url, poll_interval=0.01)
        # aborting the join must not leak the application on the cluster
        assert rm.killed == [{"state": "KILLED"}]
    finally:
        rm.close()


def test_rest_poll_tolerates_transient_rm_blips():
    """Brief RM unavailability (scripted 503s) must not abort the job;
    the real terminal state after the blip is what's reported."""
    rm = MockRM(states=("ACCEPTED", "ERR", "ERR", "FAILED"))
    try:
        args = _args()
        args.num_servers = 0
        with pytest.raises(RuntimeError, match="FAILED"):
            submit_via_rest(args, rm.url, poll_interval=0.01)
    finally:
        rm.close()


def test_rest_context_quotes_command_args():
    args = _args()
    args.command = ["python", "train.py", "--name", "run 1"]
    ctx = build_rest_context(args, "app_1", {})
    cmd = ctx["am-container-spec"]["commands"]["command"]
    assert "--name 'run 1'" in cmd


def test_rest_dry_run_prints_context(capsys, monkeypatch):
    monkeypatch.setenv("DMLC_YARN_REST", "http://rm.invalid:8088")
    from dmlc_core_tpu.tracker.backends import yarn as yarn_backend

    args = _args(["--dry-run"])
    yarn_backend.submit(args)
    out = capsys.readouterr().out
    assert "POST http://rm.invalid:8088/ws/v1/cluster/apps" in out
    ctx = json.loads(out[out.index("{"):])
    assert ctx["application-name"] == "dmlc-tpu-job"


# -- the Python AM ------------------------------------------------------------

def test_task_env_strips_role_sets_task_id():
    env = task_env({"DMLC_ROLE": "worker", "X": "1"}, 3)
    assert "DMLC_ROLE" not in env
    assert env["DMLC_TASK_ID"] == "3" and env["X"] == "1"


AM_TASK = r"""
import os, sys
marker = os.path.join(
    os.environ["AM_TEST_DIR"],
    f"t{os.environ['DMLC_TASK_ID']}.a{os.environ['DMLC_NUM_ATTEMPT']}."
    + os.environ["DMLC_ROLE"],
)
open(marker, "w").close()
# task 1 fails on its first attempt only → must be relaunched
if os.environ["DMLC_TASK_ID"] == "1" and os.environ["DMLC_NUM_ATTEMPT"] == "0":
    sys.exit(9)
"""


def _run_am(tmp_path, env_extra, code=AM_TASK):
    script = tmp_path / "task.py"
    script.write_text(code)
    env = os.environ.copy()
    env.update(
        AM_TEST_DIR=str(tmp_path),
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        **env_extra,
    )
    return subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.tracker.yarn_am",
         sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=120,
    ), sorted(p.name for p in tmp_path.glob("t*.a*"))


def test_yarn_am_supervises_and_relaunches(tmp_path):
    """3 tasks in-container: roles derived from task id, the crashing
    task relaunched with DMLC_NUM_ATTEMPT bumped, job exits 0."""
    proc, markers = _run_am(
        tmp_path,
        {"DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "1",
         "DMLC_MAX_ATTEMPT": "3"},
    )
    assert proc.returncode == 0, proc.stderr
    assert markers == [
        "t0.a0.worker", "t1.a0.worker", "t1.a1.worker", "t2.a0.server"
    ]


def test_yarn_am_aborts_past_budget(tmp_path):
    always_fail = AM_TASK.replace(
        'and os.environ["DMLC_NUM_ATTEMPT"] == "0"', ""
    )
    proc, markers = _run_am(
        tmp_path,
        {"DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "0",
         "DMLC_MAX_ATTEMPT": "2"},
        code=always_fail,
    )
    assert proc.returncode == 1
    assert "aborted" in proc.stderr
    # task 1 burned exactly its 2-attempt budget
    assert markers.count("t1.a0.worker") == 1 and "t1.a1.worker" in markers
    assert "t1.a2.worker" not in markers


def test_jar_path_error_mentions_rest_alternative(monkeypatch):
    monkeypatch.delenv("DMLC_YARN_REST", raising=False)
    monkeypatch.delenv("HADOOP_HOME", raising=False)
    from dmlc_core_tpu.tracker.backends import yarn as yarn_backend

    args = _args()
    args.num_servers = 0  # rabit branch: launch_all runs and raises
    with pytest.raises(RuntimeError, match="DMLC_YARN_REST"):
        yarn_backend.submit(args)
