"""Models/ops/parallel tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dmlc_core_tpu.models import (
    FactorizationMachine,
    LinearRegression,
    LogisticRegression,
)
from dmlc_core_tpu.ops import ell_matvec, ell_to_dense, weighted_mean
from dmlc_core_tpu.parallel import data_parallel_step, make_mesh
from dmlc_core_tpu.staging import BatchSpec, FixedShapeBatcher
from dmlc_core_tpu.data.row_block import RowBlock


def synth_batch(rng, batch=64, k=6, d=32, w_true=None):
    """Linearly separable ELL batch."""
    idx = np.stack(
        [rng.choice(d, size=k, replace=False) for _ in range(batch)]
    ).astype(np.int32)
    val = rng.normal(size=(batch, k)).astype(np.float32)
    if w_true is None:
        w_true = rng.normal(size=d).astype(np.float32)
    scores = (val * w_true[idx]).sum(axis=1)
    return {
        "indices": idx,
        "values": val,
        "nnz": np.full(batch, k, np.int32),
        "labels": (scores > 0).astype(np.float32),
        "weights": np.ones(batch, np.float32),
    }, w_true


# -- ops ---------------------------------------------------------------------

def test_ell_matvec_matches_dense():
    rng = np.random.default_rng(0)
    batch, _ = synth_batch(rng, batch=16, k=4, d=20)
    w = rng.normal(size=20).astype(np.float32)
    out = ell_matvec(batch["indices"], batch["values"], w)
    dense = np.zeros((16, 20), np.float32)
    for b in range(16):
        for j in range(4):
            dense[b, batch["indices"][b, j]] += batch["values"][b, j]
    np.testing.assert_allclose(np.asarray(out), dense @ w, rtol=1e-5)


def test_ell_to_dense_matches_batcher():
    blk = RowBlock(
        offset=np.array([0, 2, 3]),
        label=np.array([1.0, 0.0], np.float32),
        index=np.array([1, 1, 4], np.uint64),  # duplicate accumulates
        value=np.array([0.5, 0.25, 2.0], np.float32),
    )
    spec = BatchSpec(batch_size=2, layout="dense", num_features=8)
    (host,) = list(FixedShapeBatcher(spec).push(blk))
    spec_ell = BatchSpec(batch_size=2, layout="ell", max_nnz=2)
    (ell,) = list(FixedShapeBatcher(spec_ell).push(blk))
    dev = ell_to_dense(jnp.asarray(ell.indices), jnp.asarray(ell.values), 8)
    np.testing.assert_allclose(np.asarray(dev), host.x, rtol=1e-6)


def test_weighted_mean_masks_padding():
    per_row = jnp.array([1.0, 2.0, 100.0])
    w = jnp.array([1.0, 1.0, 0.0])
    assert float(weighted_mean(per_row, w)) == pytest.approx(1.5)


# -- models ------------------------------------------------------------------

def test_logistic_learns_separable():
    rng = np.random.default_rng(1)
    model = LogisticRegression(num_features=32)
    params = model.init(jax.random.PRNGKey(0))
    step = jax.jit(lambda p, b: model.sgd_step(p, b, lr=0.5))
    batch0, w_true = synth_batch(rng, batch=128, d=32)
    first_loss = float(model.loss(params, batch0))
    for _ in range(200):
        batch, _ = synth_batch(rng, batch=128, d=32, w_true=w_true)
        params, loss = step(params, batch)
    assert float(loss) < first_loss * 0.5
    test, _ = synth_batch(rng, batch=256, d=32, w_true=w_true)
    acc = float(model.accuracy(params, test))
    assert acc > 0.9, acc


def test_linear_regression_fits():
    rng = np.random.default_rng(2)
    model = LinearRegression(num_features=16)
    params = model.init(jax.random.PRNGKey(0))
    w_true = rng.normal(size=16).astype(np.float32)
    step = jax.jit(lambda p, b: model.sgd_step(p, b, lr=0.3))
    for _ in range(100):
        batch, _ = synth_batch(rng, batch=64, k=4, d=16, w_true=w_true)
        scores = (batch["values"] * w_true[batch["indices"]]).sum(axis=1)
        batch["labels"] = scores.astype(np.float32)  # regression targets
        params, loss = step(params, batch)
    assert float(loss) < 0.05


def test_fm_loss_decreases():
    rng = np.random.default_rng(3)
    model = FactorizationMachine(num_features=32, embed_dim=4)
    params = model.init(jax.random.PRNGKey(0))
    step = jax.jit(lambda p, b: model.sgd_step(p, b, lr=0.2))
    batch, w_true = synth_batch(rng, batch=128, d=32)
    first = float(model.loss(params, batch))
    for _ in range(80):
        b, _ = synth_batch(rng, batch=128, d=32, w_true=w_true)
        params, loss = step(params, b)
    assert float(loss) < first


def test_dense_layout_forward_matches_ell():
    rng = np.random.default_rng(4)
    model = LogisticRegression(num_features=16)
    params = model.init(jax.random.PRNGKey(1))
    ell, _ = synth_batch(rng, batch=8, k=3, d=16)
    dense_x = np.zeros((8, 16), np.float32)
    for b in range(8):
        for j in range(3):
            dense_x[b, ell["indices"][b, j]] += ell["values"][b, j]
    dense = {
        "x": dense_x, "labels": ell["labels"], "weights": ell["weights"],
    }
    np.testing.assert_allclose(
        np.asarray(model.forward(params, ell)),
        np.asarray(model.forward(params, dense)),
        rtol=1e-5,
    )


# -- parallel ----------------------------------------------------------------

def test_make_mesh_shapes():
    mesh = make_mesh(devices=jax.devices("cpu"))
    assert mesh.devices.shape == (8,) and mesh.axis_names == ("data",)
    mesh2 = make_mesh((4, -1), ("data", "model"), devices=jax.devices("cpu"))
    assert mesh2.devices.shape == (4, 2)
    with pytest.raises(Exception, match="mesh shape"):
        make_mesh((3, 2), ("data", "model"), devices=jax.devices("cpu"))


def test_data_parallel_step_matches_single_device():
    rng = np.random.default_rng(5)
    model = LogisticRegression(num_features=32)
    params = model.init(jax.random.PRNGKey(0))
    batch, _ = synth_batch(rng, batch=64, d=32)

    def train(p, b):
        return model.sgd_step(p, b, lr=0.5)

    single_params, single_loss = jax.jit(train)(params, batch)
    mesh = make_mesh(devices=jax.devices("cpu"))
    spmd = data_parallel_step(train, mesh, donate_params=False)
    spmd_params, spmd_loss = spmd(params, batch)
    assert float(spmd_loss) == pytest.approx(float(single_loss), rel=1e-5)
    np.testing.assert_allclose(
        np.asarray(spmd_params["w"]), np.asarray(single_params["w"]), rtol=1e-5
    )
    # batch really lands sharded over the 8 devices
    assert len(spmd_params["w"].sharding.device_set) == 8


def test_tensor_parallel_fm_matches_replicated():
    rng = np.random.default_rng(6)
    model = FactorizationMachine(num_features=32, embed_dim=8)
    params = model.init(jax.random.PRNGKey(0))
    batch, _ = synth_batch(rng, batch=32, d=32)

    def train(p, b):
        return model.sgd_step(p, b, lr=0.1)

    ref_params, ref_loss = jax.jit(train)(params, batch)
    mesh = make_mesh((4, 2), ("data", "model"), devices=jax.devices("cpu"))
    spmd = data_parallel_step(
        train, mesh, param_rules={"v": P(None, "model")}, donate_params=False
    )
    tp_params, tp_loss = spmd(params, batch)
    assert float(tp_loss) == pytest.approx(float(ref_loss), rel=1e-4)
    np.testing.assert_allclose(
        np.asarray(tp_params["v"]), np.asarray(ref_params["v"]), rtol=1e-4
    )


# -- driver entry points -----------------------------------------------------

def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, (params, batch) = ge.entry()
    out = jax.jit(fn)(params, batch)
    assert np.asarray(out).shape == (8,)
    assert np.all(np.isfinite(np.asarray(out)))


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_dryrun_multichip_odd():
    import __graft_entry__ as ge

    ge.dryrun_multichip(1)
