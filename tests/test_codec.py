"""Compressed-block RecordIO: codec registry, block header/crc,
round-trip property tests across every codec × container × read path,
fault-injection chaos, the parallel decode pool and the decoded-block
cache (ISSUE 5 tentpole).

The load-bearing invariant everywhere: the DECODED record stream is
byte-identical to what the uncompressed writer emits for the same
records — including records containing the RecordIO magic word (the
multipart escape) — and corruption/missing codecs surface as checked
errors, never garbage records.
"""

import os
import struct

import numpy as np
import pytest

from dmlc_core_tpu.io import codec as codec_mod
from dmlc_core_tpu.io import split as io_split
from dmlc_core_tpu.io.codec import (
    DecodedBlockCache,
    available_codecs,
    decode_block,
    encode_block,
    get_codec,
)
from dmlc_core_tpu.io.recordio import (
    KMAGIC,
    IndexedRecordIOWriter,
    RecordIOChunkReader,
    RecordIOReader,
    chunk_has_compressed,
    decode_chunk,
)
from dmlc_core_tpu.io.stream import FileStream
from dmlc_core_tpu.utils.logging import Error

MAGIC = struct.pack("<I", KMAGIC)

# every codec this host has; raw/zlib/gzip are stdlib-backed and always
# present, zstd/lz4 join when their packages are installed
CODECS = available_codecs()


def _records(n=300, seed=0):
    """Mixed-size records, ~1 in 9 carrying an ALIGNED magic word so
    multipart escape chains occur inside block payloads."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        body = bytearray(rng.bytes(16 + (i * 7) % 53))
        if i % 9 == 0:
            body[4:8] = MAGIC
        if i % 31 == 0:
            body[0:4] = MAGIC  # magic at offset 0
        out.append(bytes(body) + str(i).encode())
    out[0] = b""  # empty record edge case
    return out


RECORDS = _records()


def _write(tmp_path, codec, records=RECORDS, block_bytes=768, name=None):
    rec = str(tmp_path / (name or f"d_{codec or 'v1'}.rec"))
    idx = rec + ".idx"
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(
            f, fi, codec=codec, block_bytes=block_bytes
        )
        for r in records:
            w.write_record(r)
        w.flush()
    return rec, idx


# -- registry ----------------------------------------------------------------
def test_registry_stdlib_codecs_always_available():
    assert {"raw", "zlib", "gzip"} <= set(CODECS)
    for name in CODECS:
        c = get_codec(name)
        assert get_codec(c.codec_id) is c and get_codec(c) is c


def test_registry_unknown_and_unavailable_fail_loudly():
    with pytest.raises(Error, match="unknown codec"):
        get_codec("snappy")
    with pytest.raises(Error, match="codec id"):
        get_codec(250)
    for name in ("zstd", "lz4"):
        if name not in CODECS:
            with pytest.raises(Error, match="unavailable"):
                get_codec(name)


@pytest.mark.parametrize("codec", CODECS)
def test_codec_compress_roundtrip_and_levels(codec):
    c = get_codec(codec)
    data = b"abc" * 5000 + os.urandom(256)
    assert c.decompress(c.compress(data), len(data)) == data
    if c.default_level is not None:
        small = c.compress(data, c.default_level)
        assert c.decompress(small, len(data)) == data


@pytest.mark.parametrize("codec", CODECS)
def test_codec_streaming_matches_whole_buffer(codec):
    c = get_codec(codec)
    chunks = [os.urandom(100), b"x" * 4096, b"", b"tail"]
    whole = b"".join(chunks)
    streamed = b"".join(c.compress_stream(iter(chunks)))
    assert b"".join(c.decompress_stream([streamed])) == whole
    # chunked decompress too
    halves = [streamed[: len(streamed) // 2], streamed[len(streamed) // 2 :]]
    assert b"".join(c.decompress_stream(halves)) == whole


# -- block header / crc ------------------------------------------------------
def test_block_header_roundtrip_and_corruption():
    raw = b"payload" * 100
    blob = encode_block(raw, 7, "zlib")
    got, n = decode_block(blob)
    assert got == raw and n == 7

    # flip a bit in the compressed payload: either the codec framing or
    # the crc must catch it — checked Error, never silent garbage
    bad = bytearray(blob)
    bad[-3] ^= 0xFF
    with pytest.raises(Error):
        decode_block(bytes(bad))

    # corrupt the stored crc itself: decode succeeds, checksum doesn't
    bad = bytearray(blob)
    bad[12] ^= 0xFF  # crc32 field of the 16-byte header
    with pytest.raises(Error, match="crc"):
        decode_block(bytes(bad))

    with pytest.raises(Error, match="shorter"):
        decode_block(blob[:10])
    with pytest.raises(Error, match="version"):
        decode_block(bytes([blob[0], 99]) + blob[2:])


def test_truncated_block_detected():
    raw = os.urandom(4096)
    blob = encode_block(raw, 1, "raw")
    with pytest.raises(Error):
        decode_block(blob[:-100])


# -- round-trip property: codec × container × read path ----------------------
@pytest.mark.parametrize("codec", CODECS)
def test_plain_container_all_read_paths(codec, tmp_path):
    rec, _ = _write(tmp_path, codec)
    data = open(rec, "rb").read()
    # stream reader decodes transparently
    with FileStream(rec, "r") as f:
        assert list(RecordIOReader(f)) == RECORDS
    # decode_chunk + sub-split chunk reader (the thread fan-out path):
    # every (part, num_parts) covers each record exactly once
    assert chunk_has_compressed(data)
    dec = decode_chunk(data)
    for nparts in (1, 2, 3, 7):
        got = []
        for p in range(nparts):
            got.extend(bytes(r) for r in RecordIOChunkReader(dec, p, nparts))
        assert got == RECORDS, nparts
    # sharded byte-range splitter (magic scan over compressed heads)
    for nparts in (1, 3):
        got = []
        for p in range(nparts):
            sp = io_split.create(rec, p, nparts, type="recordio",
                                 threaded=False)
            sp.hint_chunk_size(512)  # many tiny chunks
            got.extend(bytes(r) for r in sp)
            sp.close()
        assert sorted(got) == sorted(RECORDS), nparts


@pytest.mark.parametrize("codec", CODECS)
@pytest.mark.parametrize("shuffle", ("0", "record", "batch", "window"))
def test_indexed_container_all_modes_sharded(codec, shuffle, tmp_path):
    rec, idx = _write(tmp_path, codec)
    for nparts in (1, 2):
        got = []
        for p in range(nparts):
            sp = io_split.create(
                f"{rec}?index={idx}&shuffle={shuffle}&seed=5"
                f"&window=64&merge_gap=96&batch_size=32",
                p, nparts, type="recordio", threaded=False,
            )
            got.extend(bytes(r) for r in sp)
            sp.close()
        assert sorted(got) == sorted(RECORDS), (shuffle, nparts)


def test_window_order_identical_to_uncompressed_record_shuffle(tmp_path):
    """Same (seed, epoch) ⇒ the compressed window shuffle must emit the
    EXACT v1 per-record permutation order — compression changes how the
    bytes travel, never the order they leave."""
    v1rec, v1idx = _write(tmp_path, None)
    rec, idx = _write(tmp_path, "zlib")

    def stream(rc, ix, mode):
        sp = io_split.create(
            f"{rc}?index={ix}&shuffle={mode}&seed=11&window=64",
            0, 1, type="recordio", threaded=False,
        )
        out = [bytes(r) for r in sp]
        sp.close()
        return out

    want = stream(v1rec, v1idx, "record")
    assert stream(rec, idx, "window") == want
    assert stream(rec, idx, "record") == want


def test_uncompressed_files_read_bit_identically(tmp_path):
    """Format safety: the v1 path through the compressed-aware readers
    is bit-identical — decode_chunk passes a v1 chunk through as the
    SAME object, and the sidecar keeps plain offsets."""
    rec, idx = _write(tmp_path, None)
    data = open(rec, "rb").read()
    assert not chunk_has_compressed(data)
    assert decode_chunk(data) is data
    assert ":" not in open(idx).read()
    with FileStream(rec, "r") as f:
        assert list(RecordIOReader(f, allow_compressed=False)) == RECORDS


def test_threaded_and_cached_wrappers_over_compressed(tmp_path):
    """The prefetch thread pulls chunks that decode on the producer
    side (network/decode overlap), and a #cachefile caches the DECODED
    chunks — replay costs no second decompression."""
    rec, _ = _write(tmp_path, "zlib")
    sp = io_split.create(rec, 0, 1, type="recordio")  # threaded default
    assert sorted(bytes(r) for r in sp) == sorted(RECORDS)
    sp.close()

    cache = str(tmp_path / "chunks.cache")
    sp = io_split.create(rec + "#" + cache, 0, 1, type="recordio")
    first = [bytes(r) for r in sp]
    sp.before_first()  # replays from the cache file
    second = [bytes(r) for r in sp]
    sp.close()
    assert first == second and sorted(first) == sorted(RECORDS)


# -- loud failure on old readers ---------------------------------------------
def test_v1_only_readers_reject_compressed_blocks(tmp_path):
    rec, idx = _write(tmp_path, "zlib")
    data = open(rec, "rb").read()
    with FileStream(rec, "r") as f:
        with pytest.raises(Error, match="v1-only"):
            RecordIOReader(f, allow_compressed=False).next_record()
    with pytest.raises(Error, match="decode_chunk"):
        RecordIOChunkReader(data, 0, 1).next_record()
    # a v1 index parser chokes on the block:in-offset column — loudly
    with pytest.raises(ValueError):
        [int(tok) for tok in open(idx).read().split()]


def test_compressed_index_requires_consistency(tmp_path):
    rec, idx = _write(tmp_path, "zlib")
    broken = str(tmp_path / "mixed.idx")
    lines = open(idx).read().splitlines()
    lines[1] = "1\t64"  # a v1 offset amid block:in pairs
    open(broken, "w").write("\n".join(lines) + "\n")
    with pytest.raises(Error, match="mixes"):
        io_split.create(f"{rec}?index={broken}", 0, 1, type="recordio",
                        threaded=False)


# -- corruption through the read path ----------------------------------------
def test_corrupt_block_surfaces_checked_error(tmp_path):
    rec, idx = _write(tmp_path, "zlib")
    blob = bytearray(open(rec, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # inside some block's compressed bytes
    bad = str(tmp_path / "bad.rec")
    open(bad, "wb").write(bytes(blob))
    sp = io_split.create(bad, 0, 1, type="recordio", threaded=False)
    with pytest.raises(Error):
        list(sp)
    sp.close()


# -- fault-injection chaos (PR 2 suite over compressed spans) ----------------
@pytest.mark.parametrize("shuffle", ("0", "window"))
def test_fault_injected_reads_heal_byte_identical(shuffle, tmp_path):
    from dmlc_core_tpu.io.faults import wrap_uri

    rec, idx = _write(tmp_path, "zlib")
    sugar = f"?index={idx}&shuffle={shuffle}&seed=2&window=64"

    def run(uri):
        codec_mod.default_decode_cache().clear()
        sp = io_split.create(uri + sugar, 0, 1, type="recordio",
                             threaded=False)
        out = [bytes(r) for r in sp]
        stats = sp.io_stats()
        sp.close()
        return out, stats

    clean, _ = run(rec)
    chaos, stats = run(wrap_uri(rec, "resets=2,short=2,errors=1,seed=7"))
    assert chaos == clean == [
        r for r in clean
    ] and sorted(clean) == sorted(RECORDS)
    assert stats["faults_injected"] > 0 and stats["retries"] > 0


def test_latency_spike_schedule_decodes_identically(tmp_path):
    """The fault-free latency-spike schedule (pure delay, no error):
    the codec path must return identical bytes — the bench acceptance
    shape (codec wins when the link, not the CPU, is the bottleneck)."""
    from dmlc_core_tpu.io.faults import wrap_uri

    rec, _ = _write(tmp_path, "zlib")
    sp = io_split.create(
        wrap_uri(rec, "latency_ms=1,spikes=2,seed=3"), 0, 1,
        type="recordio", threaded=False,
    )
    assert sorted(bytes(r) for r in sp) == sorted(RECORDS)
    sp.close()


# -- decoded-block cache ------------------------------------------------------
def test_decoded_block_cache_lru_bounds():
    c = DecodedBlockCache(100)
    c.put("a", b"x" * 40)
    c.put("b", b"y" * 40)
    assert c.get("a") == b"x" * 40
    c.put("c", b"z" * 40)  # evicts LRU ("b" — "a" was touched)
    assert c.get("b") is None and c.get("a") is not None
    assert c.nbytes <= 100
    c.put("big", b"q" * 101)  # larger than the budget: not retained
    assert c.get("big") is None
    c.clear()
    assert len(c) == 0 and c.nbytes == 0


def test_second_epoch_serves_from_cache(tmp_path):
    """Acceptance: decoded-block cache hit rate > 0.9 on a second epoch
    of shuffle='window' over the same shard."""
    rec, idx = _write(tmp_path, "zlib")
    codec_mod.default_decode_cache().clear()
    sp = io_split.create(
        f"{rec}?index={idx}&shuffle=window&seed=4&window=64",
        0, 1, type="recordio", threaded=False,
    )
    e1 = [bytes(r) for r in sp]
    h1, m1 = sp.decode_cache_hits, sp.decode_cache_misses
    assert m1 > 0  # first epoch decoded blocks
    sp.before_first()
    e2 = [bytes(r) for r in sp]
    h2 = sp.decode_cache_hits - h1
    m2 = sp.decode_cache_misses - m1
    st = sp.io_stats()
    sp.close()
    assert sorted(e1) == sorted(e2) == sorted(RECORDS)
    assert h2 / max(h2 + m2, 1) > 0.9
    assert st["decode_cache_hits"] == sp.decode_cache_hits


def test_telemetry_counters_tick(tmp_path):
    from dmlc_core_tpu.telemetry import default_registry

    reg = default_registry()
    raw0 = reg.counter("io.codec.bytes_raw").value()
    comp0 = reg.counter("io.codec.bytes_compressed").value()
    dec0 = reg.histogram("io.codec.decode_seconds").snapshot()["count"]
    rec, _ = _write(tmp_path, "zlib")
    with FileStream(rec, "r") as f:
        assert list(RecordIOReader(f)) == RECORDS
    assert reg.counter("io.codec.bytes_raw").value() > raw0
    assert reg.counter("io.codec.bytes_compressed").value() > comp0
    assert (
        reg.histogram("io.codec.decode_seconds").snapshot()["count"] > dec0
    )


# -- generic parser over compressed rowrec ------------------------------------
def test_rowrec_codec_roundtrip(tmp_path):
    from dmlc_core_tpu.data import create_row_block_iter
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import write_rowrec

    rng = np.random.default_rng(1)
    n, k = 64, 3
    blk = RowBlock(
        offset=np.arange(n + 1, dtype=np.int64) * k,
        label=rng.integers(0, 2, n).astype(np.float32),
        index=rng.integers(0, 100, n * k).astype(np.uint32),
        value=rng.normal(size=n * k).astype(np.float32),
    )
    rec = str(tmp_path / "rows.rec")
    with FileStream(rec, "w") as f:
        assert write_rowrec(f, [blk], codec="zlib") == n
    labels = []
    vals = []
    for b in create_row_block_iter(rec + "?format=rowrec"):
        labels.extend(np.asarray(b.label).tolist())
        vals.extend(np.asarray(b.value).tolist())
    assert labels == blk.label.tolist()
    np.testing.assert_array_equal(np.asarray(vals, np.float32), blk.value)


# -- resume / skip_records on compressed windows ------------------------------
def test_skip_records_window_boundary_compressed(tmp_path):
    rec, idx = _write(tmp_path, "zlib")
    full = io_split.create(
        f"{rec}?index={idx}&shuffle=window&seed=6&window=50",
        0, 1, type="recordio", threaded=False,
    )
    want = [bytes(r) for r in full]
    full.close()
    resumed = io_split.create(
        f"{rec}?index={idx}&shuffle=window&seed=6&window=50"
        f"&skip_records=100",
        0, 1, type="recordio", threaded=False,
    )
    got = [bytes(r) for r in resumed]
    resumed.close()
    assert got == want[100:]
