"""Durable tracker control plane (tracker/journal.py + recovery wiring,
docs/robustness.md): WAL framing damage shapes (torn tail truncated,
CRC corruption refused), snapshot+WAL replay equivalence, the shard
service's conservative lease expiry on restore, rank re-answering, the
universal reconnect dial (storm of clients riding out an outage), the
heartbeat's never-raise contract while the tracker is down, and the
chaos drill — a standalone tracker SIGKILLed mid-epoch, relaunched on
the same port from its journal, every micro-shard exactly-once."""

import copy
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dmlc_core_tpu.tracker import journal as jn
from dmlc_core_tpu.tracker.client import RabitWorker
from dmlc_core_tpu.tracker.protocol import (
    MAGIC,
    FramedSocket,
    connect_worker_retry,
    make_listener,
)
from dmlc_core_tpu.tracker.shardsvc import ShardLeaseClient, ShardService
from dmlc_core_tpu.tracker.tracker import RabitTracker


# -- journal unit: append / replay / damage ------------------------------------

def _sample_records():
    return [
        (jn.K_DATASET_SWITCH, {"fileset": "fs://a"}),
        (jn.K_SHARD_GRANT,
         {"epoch": 0, "shard": 0, "rank": 1, "fileset": "fs://a",
          "n_shards": 4}),
        (jn.K_SHARD_GRANT,
         {"epoch": 0, "shard": 1, "rank": 2, "fileset": "fs://a",
          "n_shards": 4}),
        (jn.K_SHARD_DONE, {"epoch": 0, "shard": 0, "rank": 1}),
        (jn.K_SHARD_RELEASE, {"epoch": 0, "shard": 1, "rank": 2}),
        (jn.K_RANK_ASSIGN,
         {"jobid": "job0", "rank": 0, "world": 2, "topo_epoch": 1}),
        (jn.K_AUTOSCALE,
         {"target": 3, "cost_spent": 42.5, "dwell_elapsed": 1.5,
          "last_direction": 1, "direction_changes": 1}),
    ]


def test_replay_equals_live_fold(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d)
    assert not j.recovered
    for kind, fields in _sample_records():
        j.append(kind, **fields)
    live = copy.deepcopy(j.state)
    j.close()
    state, last_seq, info = jn.read_journal(d)
    assert state == live
    assert last_seq == len(_sample_records())
    assert info["torn_tail_at"] is None
    # the ledger facts themselves
    ep = state["shards"]["epochs"]["0"]
    assert ep["done"] == {"0": 1}
    # release keeps the shard outstanding: grant history must outlive
    # it so a post-recovery late done is honored, not "never granted"
    assert ep["outstanding"] == {"1": 2}
    assert state["ranks"]["job0"]["rank"] == 0
    assert state["autoscale"]["cost_spent"] == 42.5


def test_double_replay_byte_identical(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d)
    for kind, fields in _sample_records():
        j.append(kind, **fields)
    j.close()
    one = jn.read_journal(d)
    two = jn.read_journal(d)
    assert json.dumps(one, sort_keys=True) == json.dumps(two, sort_keys=True)


def test_torn_tail_truncated_mid_record(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d)
    for kind, fields in _sample_records():
        j.append(kind, **fields)
    live = copy.deepcopy(j.state)
    j.close()
    wal = os.path.join(d, jn.WAL_NAME)
    clean_size = os.path.getsize(wal)
    # a crash mid-append: full header promising more payload than exists
    with open(wal, "ab") as f:
        f.write(struct.pack("<II", 0xDEADBEEF, 1 << 10))
        f.write(b"partial")
    state, last_seq, info = jn.read_journal(d)
    assert info["torn_tail_at"] == clean_size
    assert state == live  # everything before the tear survives
    # a writable open truncates the tear in place and appends cleanly
    j2 = jn.Journal(d)
    assert j2.recovered
    assert os.path.getsize(wal) == clean_size
    j2.append(jn.K_SHARD_DONE, epoch=0, shard=1, rank=2)
    j2.close()
    state3, _, info3 = jn.read_journal(d)
    assert info3["torn_tail_at"] is None
    assert state3["shards"]["epochs"]["0"]["done"] == {"0": 1, "1": 2}
    # header-only tear (shorter than the frame header) also flagged
    with open(wal, "ab") as f:
        f.write(b"\x01\x02")
    _, _, info4 = jn.read_journal(d)
    assert info4["torn_tail_at"] == os.path.getsize(wal) - 2


def test_crc_corruption_refused_but_inspectable(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d)
    for kind, fields in _sample_records():
        j.append(kind, **fields)
    j.close()
    wal = os.path.join(d, jn.WAL_NAME)
    raw = bytearray(open(wal, "rb").read())
    raw[12] ^= 0xFF  # inside the first record's payload
    open(wal, "wb").write(bytes(raw))
    with pytest.raises(jn.JournalError):
        jn.read_journal(d)
    with pytest.raises(jn.JournalError):
        jn.Journal(d)  # the writable open is strict too
    dump = jn.inspect_journal(d)  # lenient: operators still get a look
    assert dump["crc_failures"] == 1
    assert dump["records"][0]["crc_ok"] is False
    assert all(r["crc_ok"] for r in dump["records"][1:])


def test_snapshot_compacts_wal_and_replays(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d, snapshot_every=3)  # auto-snapshot mid-stream
    for kind, fields in _sample_records():
        j.append(kind, **fields)
    live = copy.deepcopy(j.state)
    seq = j.seq
    j.close()
    assert os.path.exists(os.path.join(d, jn.SNAPSHOT_NAME))
    # WAL only holds records SINCE the last snapshot
    records, torn = jn._scan_wal(os.path.join(d, jn.WAL_NAME), strict=True)
    assert torn is None and len(records) < len(_sample_records())
    state, last_seq, info = jn.read_journal(d)
    assert state == live and last_seq == seq
    assert info["snapshot_seq"] > 0


def test_corrupt_snapshot_refused(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d)
    j.append(jn.K_DATASET_SWITCH, fileset="fs://a")
    j.snapshot()
    j.close()
    snap = os.path.join(d, jn.SNAPSHOT_NAME)
    open(snap, "w").write("{not json")
    with pytest.raises(jn.JournalError):
        jn.read_journal(d)
    assert "error" in jn.inspect_journal(d)["snapshot"]


def test_sync_policy_env(monkeypatch):
    monkeypatch.delenv("DMLC_TRACKER_JOURNAL_SYNC", raising=False)
    assert jn.default_sync_policy() == "always"
    monkeypatch.setenv("DMLC_TRACKER_JOURNAL_SYNC", "interval")
    assert jn.default_sync_policy() == "interval"
    monkeypatch.setenv("DMLC_TRACKER_JOURNAL_SYNC", "bogus")
    assert jn.default_sync_policy() == "always"


def test_unknown_record_kind_skipped(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d)
    j.append("kind_from_the_future", payload="whatever")
    j.append(jn.K_DATASET_SWITCH, fileset="fs://a")
    j.close()
    state, last_seq, _ = jn.read_journal(d)
    assert last_seq == 2
    assert state["shards"]["fileset"] == "fs://a"


# -- shard service restore: conservative expiry --------------------------------

def test_service_restore_conservative_expiry(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d)
    svc = ShardService(n_workers=2, oversplit=2, journal=j)  # 4 shards
    r = svc.lease(rank=0, epoch=0, fileset="fs://x")
    assert r["status"] == "lease"
    first = r["shard"]
    assert svc.done(0, 0, first, "fs://x")["status"] == "recorded"
    r2 = svc.lease(rank=1, epoch=0, fileset="fs://x")
    held = r2["shard"]
    j.close()

    # "relaunch": a fresh journal + service seeded from the replay
    j2 = jn.Journal(d)
    assert j2.recovered
    svc2 = ShardService(n_workers=2, oversplit=2, journal=j2)
    summary = svc2.restore(j2.state)
    assert summary["completions_restored"] == 1
    assert summary["leases_expired"] == 1  # held-but-not-done expired
    # the committed shard stays committed: duplicate, not re-granted
    assert svc2.done(0, 0, first, "fs://x")["status"] == "duplicate"
    # a LATE done for the shard leased before the crash is honored —
    # the client committed its output while the tracker was dead
    assert svc2.done(1, 0, held, "fs://x")["status"] == "recorded"
    # drain the rest: every shard granted exactly once overall
    seen = set()
    while True:
        g = svc2.lease(rank=0, epoch=0, fileset="fs://x")
        if g["status"] != "lease":
            break
        assert g["shard"] not in (first, held)
        assert g["shard"] not in seen
        seen.add(g["shard"])
        svc2.done(0, 0, g["shard"], "fs://x")
    assert len(seen) == 2  # 4 shards total - first - held
    assert svc2.all_complete()


def test_tracker_seeds_rank_memo_from_journal(tmp_path):
    d = str(tmp_path / "j")
    j = jn.Journal(d)
    j.append(jn.K_RANK_ASSIGN, jobid="7", rank=1, world=2, topo_epoch=1)
    j.append(jn.K_RANK_ASSIGN, jobid="9", rank=0, world=2, topo_epoch=1)
    j.close()
    t = RabitTracker("127.0.0.1", 2, journal_dir=d)
    try:
        assert t._recovered_ranks == {"7": 1, "9": 0}
        assert t._topo_epoch == 2  # next generation
        assert t.recovery_summary["ranks_recovered"] == 2
    finally:
        t.close()


# -- universal reconnect dial --------------------------------------------------

class _LateTracker(threading.Thread):
    """A tracker-shaped listener that starts accepting after a delay —
    the crash+relaunch window a reconnecting client rides out."""

    def __init__(self, port: int, delay: float, n_accepts: int) -> None:
        super().__init__(daemon=True)
        self.port = port
        self.delay = delay
        self.n_accepts = n_accepts
        self.accepted = 0

    def run(self) -> None:
        time.sleep(self.delay)
        srv = make_listener("127.0.0.1", self.port, backlog=64)
        try:
            for _ in range(self.n_accepts):
                conn, _ = srv.accept()
                fs = FramedSocket(conn)
                assert fs.recv_int() == MAGIC
                fs.send_int(MAGIC)
                fs.recv_int()  # rank
                fs.recv_int()  # world
                fs.recv_str()  # jobid
                fs.recv_str()  # cmd
                self.accepted += 1
                fs.close()
        finally:
            srv.close()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_connect_worker_retry_rides_outage():
    port = _free_port()
    srv = _LateTracker(port, delay=0.6, n_accepts=1)
    srv.start()
    fs = connect_worker_retry(
        "127.0.0.1", port, 0, -1, "job", "print", retry_secs=15.0
    )
    fs.close()
    srv.join(timeout=10)
    assert srv.accepted == 1


def test_connect_worker_retry_zero_budget_fails_fast():
    port = _free_port()  # nothing listening
    t0 = time.monotonic()
    with pytest.raises((ConnectionError, OSError)):
        connect_worker_retry(
            "127.0.0.1", port, 0, -1, "job", "print", retry_secs=0
        )
    assert time.monotonic() - t0 < 2.0


def test_reconnect_storm_all_clients_within_budget():
    """8 clients dialing a down tracker: every one re-leases once it
    relaunches, inside the retry budget, jittered (no client needs the
    whole budget, none gives up)."""
    n = 8
    port = _free_port()
    srv = _LateTracker(port, delay=0.8, n_accepts=n)
    srv.start()
    errors = []

    def client(i: int) -> None:
        try:
            fs = connect_worker_retry(
                "127.0.0.1", port, i, -1, f"job{i}", "print",
                retry_secs=20.0,
            )
            fs.close()
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append((i, e))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    srv.join(timeout=10)
    assert srv.accepted == n
    assert time.monotonic() - t0 < 20.0


# -- satellite: heartbeat never raises while the tracker is down ---------------

def test_heartbeat_tracker_down_never_raises(monkeypatch):
    monkeypatch.setenv("DMLC_HEARTBEAT_RETRY_SECS", "0.2")
    w = RabitWorker("127.0.0.1", _free_port(), jobid="0")
    w.rank = 0  # heartbeat requires a completed start(); fake the rank
    w._ts_seq = 7
    t0 = time.monotonic()
    w.heartbeat({"counters": {"x": 1}})  # must return, not raise
    assert time.monotonic() - t0 < 5.0
    # the sample stays un-shipped: seq NOT advanced, next tick re-ships
    assert w._ts_seq == 7


def test_heartbeat_reships_after_tracker_returns(monkeypatch):
    """The tick after an outage ships successfully (regression pin for
    the mark-unshipped-retry-next-tick contract)."""
    monkeypatch.setenv("DMLC_HEARTBEAT_RETRY_SECS", "0.2")
    t = RabitTracker("127.0.0.1", 1)
    t.start(1)
    try:
        w = RabitWorker("127.0.0.1", t.port, jobid="0")
        w.rank = 0
        w.heartbeat({"counters": {"x": 1}})
        deadline = time.monotonic() + 5.0
        while 0 not in t.metrics.per_rank() and (
            time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert 0 in t.metrics.per_rank()
    finally:
        t.close()


# -- the chaos drill -----------------------------------------------------------

def _spawn_tracker(journal_dir, ep_file, n_workers, port, port_end):
    try:
        os.remove(ep_file)
    except OSError:
        pass
    return subprocess.Popen([
        sys.executable, "-m", "dmlc_core_tpu.tracker.tracker",
        "--host-ip", "127.0.0.1", "--port", str(port),
        "--port-end", str(port_end), "--num-workers", str(n_workers),
        "--journal", journal_dir, "--endpoint-file", ep_file,
    ])


def _await_endpoint(proc, ep_file, timeout=15.0):
    deadline = time.monotonic() + timeout
    while not os.path.exists(ep_file):
        assert proc.poll() is None, f"tracker died rc={proc.poll()}"
        assert time.monotonic() < deadline, "endpoint file never appeared"
        time.sleep(0.05)
    ep = json.load(open(ep_file))
    return ep["host"], int(ep["port"])


def test_tracker_kill_recovery_exactly_once(tmp_path, monkeypatch):
    """The acceptance drill in miniature: 3 lease-holding workers, the
    tracker SIGKILLed mid-epoch and relaunched on the same port from
    its journal; every micro-shard is committed exactly once and the
    fold of per-shard outputs is identical to a clean run's."""
    monkeypatch.setenv("DMLC_SHARD_OVERSPLIT", "3")
    monkeypatch.setenv("DMLC_TRACKER_RETRY_SECS", "30")
    fileset = "fs://chaos"
    n_workers, n_shards = 3, 9

    def run_drill(tag: str, kill_after: int):
        """Drain one epoch; SIGKILL+relaunch the tracker after
        ``kill_after`` commits (0 = clean run). Returns {shard: fold}
        and the commit counts per shard."""
        jdir = str(tmp_path / f"journal-{tag}")
        ep_file = str(tmp_path / f"ep-{tag}.json")
        port = _free_port()
        proc = _spawn_tracker(jdir, ep_file, n_workers, port, port + 50)
        host, bound = _await_endpoint(proc, ep_file)
        commits: dict = {}
        lock = threading.Lock()
        killed = threading.Event()
        errors: list = []

        def worker(rank: int) -> None:
            try:
                c = ShardLeaseClient(host, bound, rank=rank)
                backoffs = 0
                while True:
                    r = c.lease(0, fileset)
                    if r["status"] == "done":
                        return  # epoch fully drained by the fleet
                    if r["status"] == "wait":
                        backoffs += 1
                        if backoffs > 200:
                            raise RuntimeError("livelocked on wait")
                        time.sleep(min(0.1, r.get("backoff", 0.05)))
                        continue
                    if r["status"] != "lease":
                        raise RuntimeError(f"lease -> {r}")
                    backoffs = 0
                    shard = int(r["shard"])
                    # deterministic per-shard contribution, then commit
                    value = shard * shard + 1
                    d = c.done(0, shard, fileset)
                    if d["status"] == "recorded":
                        with lock:
                            commits[shard] = commits.get(shard, 0) + 1
                            commits.setdefault("values", {})[shard] = value
                            n_done = sum(
                                1 for k in commits if isinstance(k, int)
                            )
                        if (kill_after and n_done == kill_after
                                and not killed.is_set()):
                            killed.set()  # exactly one killer
                            proc.send_signal(signal.SIGKILL)
                            proc.wait()
                            p2 = _spawn_tracker(
                                jdir, ep_file, n_workers, bound, bound + 1
                            )
                            procs.append(p2)
                            _await_endpoint(p2, ep_file)
                    if d.get("epoch_complete"):
                        return
            except Exception as e:  # noqa: BLE001 - surfaced via assert
                errors.append((rank, e))

        procs = [proc]
        threads = [
            threading.Thread(target=worker, args=(r,))
            for r in range(n_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for p in procs:
            if p.poll() is None:
                p.terminate()
                p.wait(timeout=10)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads)
        return commits

    clean = run_drill("clean", kill_after=0)
    chaos = run_drill("chaos", kill_after=3)
    for commits in (clean, chaos):
        shards = sorted(k for k in commits if isinstance(k, int))
        assert shards == list(range(n_shards))
        # exactly once: no shard committed twice
        assert all(commits[s] == 1 for s in shards)
    # the "model": fold of deterministic per-shard contributions —
    # identical iff the same shards committed exactly once
    fold_clean = sorted(clean["values"].items())
    fold_chaos = sorted(chaos["values"].items())
    assert fold_clean == fold_chaos


def test_journal_inspect_cli(tmp_path, capsys):
    from dmlc_core_tpu import tools

    d = str(tmp_path / "j")
    j = jn.Journal(d)
    j.append(jn.K_DATASET_SWITCH, fileset="fs://a")
    j.append(jn.K_SHARD_GRANT, epoch=0, shard=0, rank=0,
             fileset="fs://a", n_shards=2)
    j.close()
    assert tools.main(["journal", "inspect", d]) == 0
    out = capsys.readouterr().out
    assert "dataset_switch" in out and "[ok]" in out
    assert tools.main(["journal", "inspect", d, "--json"]) == 0
    dump = json.loads(capsys.readouterr().out)
    assert len(dump["records"]) == 2 and dump["crc_failures"] == 0
    # CRC damage: nonzero exit + flagged record
    wal = os.path.join(d, jn.WAL_NAME)
    raw = bytearray(open(wal, "rb").read())
    raw[10] ^= 0xFF
    open(wal, "wb").write(bytes(raw))
    assert tools.main(["journal", "inspect", d]) == 1
    assert "CRC-FAIL" in capsys.readouterr().out
