"""Flight-recorder tracing (telemetry/tracing.py, ISSUE 8): bounded
per-thread span rings whose overflow is COUNTED (never silent), valid
Chrome trace-event export, per-thread monotonic order, cross-process
merge round-trips, stall attribution, and the dmlc-submit acceptance
path (workers + cache daemon + tracker in one merged timeline)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from dmlc_core_tpu.telemetry import tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh(monkeypatch):
    """Isolated recorder: cleared rings, tracing forced ON regardless
    of the environment, restored afterwards."""
    tracing.reset()
    tracing.set_enabled(True)
    yield
    tracing.set_enabled(None)
    tracing.reset()


# -- ring semantics ------------------------------------------------------------


def test_overflow_drops_are_counted_never_silent(fresh, monkeypatch):
    monkeypatch.setenv("DMLC_TRACE_BUF_KB", "1")  # -> minimum capacity
    tracing.reset()
    tracing.set_enabled(True)
    cap = tracing._ring_capacity()
    n = cap + 37
    for i in range(n):
        tracing.instant(f"ev_{i}")
    st = tracing.stats()
    (tstats,) = st["threads"].values()
    assert tstats["events"] == cap
    assert tstats["dropped"] == 37  # exact drop accounting
    # the SURVIVING events are the newest (drop-oldest), still in order
    trace = tracing.to_chrome_trace()
    names = [
        e["name"] for e in trace["traceEvents"] if e["ph"] == "i"
    ]
    assert names[0] == f"ev_{n - cap}" and names[-1] == f"ev_{n - 1}"
    # and the export declares the drops
    assert trace["otherData"]["dropped_events"] != {}


def test_disabled_records_nothing(fresh):
    tracing.set_enabled(False)
    with tracing.span("off_span"):
        pass
    tracing.instant("off_instant")
    tracing.begin("off_open")
    tracing.end()
    assert tracing.stats()["threads"] == {}


def test_env_knob_disables(fresh, monkeypatch):
    tracing.set_enabled(None)
    for off in ("off", "0", "false", ""):
        monkeypatch.setenv("DMLC_TRACE", off)
        tracing.reset()
        assert tracing.enabled() is False, off
    monkeypatch.setenv("DMLC_TRACE", "on")
    tracing.reset()
    assert tracing.enabled() is True
    monkeypatch.delenv("DMLC_TRACE")
    tracing.reset()
    assert tracing.enabled() is True  # always-on default


def test_unmatched_end_is_a_counted_drop_not_an_error(fresh):
    tracing.end()  # nothing open
    (tstats,) = tracing.stats()["threads"].values()
    assert tstats["dropped"] == 1


# -- export format -------------------------------------------------------------


def _span_events(trace):
    return [e for e in trace["traceEvents"] if e.get("ph") == "X"]


def test_export_is_valid_chrome_trace_format(fresh):
    with tracing.span("outer", label="x"):
        with tracing.span("inner"):
            pass
    tracing.instant("mark", n=2)
    tracing.counter("depth", 3)
    trace = tracing.to_chrome_trace()
    # round-trips through JSON (the on-disk format)
    trace = json.loads(json.dumps(trace))
    assert isinstance(trace["traceEvents"], list)
    phs = {e["ph"] for e in trace["traceEvents"]}
    assert phs == {"M", "X", "i", "C"}
    for ev in trace["traceEvents"]:
        assert {"ph", "name", "pid", "tid"} <= set(ev)
        if ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")
            assert "name" in ev["args"]
            continue
        assert isinstance(ev["ts"], float)
        if ev["ph"] == "X":
            assert ev["dur"] >= 0.0
        if ev["ph"] == "i":
            assert ev["s"] == "t"
        if ev["ph"] == "C":
            assert ev["args"] == {"value": 3}
    # nested spans: inner's interval lies within outer's
    spans = {e["name"]: e for e in _span_events(trace)}
    outer, inner = spans["outer"], spans["inner"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"label": "x"}


def test_per_thread_event_order_is_monotonic(fresh):
    def work():
        for _ in range(50):
            with tracing.span("t_span"):
                pass
            tracing.instant("t_mark")

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    work()  # main thread too
    trace = tracing.to_chrome_trace()
    by_tid = {}
    for ev in trace["traceEvents"]:
        if ev["ph"] in ("X", "i"):
            by_tid.setdefault(ev["tid"], []).append(ev["ts"])
    assert len(by_tid) == 5  # every thread has its own ring
    for tid, ts in by_tid.items():
        assert ts == sorted(ts), f"tid {tid} out of order"


def test_annotate_seam_feeds_the_ring(fresh):
    """ONE profiler.annotate call site feeds XProf, the histogram AND
    the flight recorder (the ISSUE 8 seam)."""
    from dmlc_core_tpu.utils.profiler import annotate

    with annotate("dmlc:seam_check"):
        time.sleep(0.001)
    spans = _span_events(tracing.to_chrome_trace())
    assert [s["name"] for s in spans] == ["dmlc:seam_check"]
    assert spans[0]["dur"] >= 1000.0  # slept >= 1ms, dur is in us


def test_dump_and_load_roundtrip(fresh, tmp_path):
    with tracing.span("persisted"):
        pass
    path = tracing.dump(str(tmp_path / "t.json"))
    trace = tracing.load_trace(path)
    assert [s["name"] for s in _span_events(trace)] == ["persisted"]
    assert trace["otherData"]["pid"] == os.getpid()
    with pytest.raises(ValueError, match="traceEvents"):
        bad = tmp_path / "bad.json"
        bad.write_text('{"not": "a trace"}')
        tracing.load_trace(str(bad))


def test_sigusr2_dump_on_demand(fresh, tmp_path, monkeypatch):
    monkeypatch.setenv("DMLC_TRACE_DIR", str(tmp_path))
    prev = signal.getsignal(signal.SIGUSR2)
    try:
        assert tracing.install_signal_dump() is True
        with tracing.span("pre_signal"):
            pass
        os.kill(os.getpid(), signal.SIGUSR2)
        # the handler runs between bytecodes; force a checkpoint
        time.sleep(0.01)
        files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
        assert len(files) == 1
        trace = tracing.load_trace(str(tmp_path / files[0]))
        assert "pre_signal" in {e["name"] for e in _span_events(trace)}
    finally:
        signal.signal(signal.SIGUSR2, prev)


def test_reset_reregisters_long_lived_pool_threads(fresh):
    """reset() must not orphan OTHER threads' rings: a pool thread that
    recorded before the reset keeps recording VISIBLY after it (the
    generation bump re-registers its TLS ring at the next event)."""
    import concurrent.futures as cf

    pool = cf.ThreadPoolExecutor(max_workers=1)
    try:
        pool.submit(tracing.instant, "before").result()
        tracing.reset()
        pool.submit(tracing.instant, "after").result()
        names = {
            e["name"]
            for e in tracing.to_chrome_trace()["traceEvents"]
            if e["ph"] == "i"
        }
        assert names == {"after"}
    finally:
        pool.shutdown()


def test_auto_install_defers_to_existing_sigusr2_handler(
    fresh, monkeypatch
):
    """The lazy signal auto-install must never clobber a handler the
    application already registered (checkpoint-on-preemption etc.) —
    only explicit install_signal_dump() overrides."""
    prev = signal.getsignal(signal.SIGUSR2)
    app_handler = lambda *_a: None  # noqa: E731
    try:
        signal.signal(signal.SIGUSR2, app_handler)
        monkeypatch.setattr(tracing, "_SIGNAL_INSTALLED", False)
        tracing.reset()  # force ring re-registration on next event
        tracing.instant("poke")  # triggers _maybe_install_signal
        assert signal.getsignal(signal.SIGUSR2) is app_handler
        # the explicit call is the sanctioned override
        assert tracing.install_signal_dump() is True
        assert signal.getsignal(signal.SIGUSR2) is not app_handler
    finally:
        signal.signal(signal.SIGUSR2, prev)


def test_trace_dump_cli_accepts_positional_pid():
    from dmlc_core_tpu import tools

    # no pid at all: usage error
    assert tools.main(["trace", "dump"]) == 2
    # a positional pid parses (the signal then fails on the bogus pid,
    # proving the value reached os.kill)
    assert tools.main(["trace", "dump", "999999999"]) == 1
    assert tools.main(["trace", "dump", "--pid", "999999999"]) == 1


# -- cross-process merge -------------------------------------------------------

_PROC_SNIPPET = """
import sys
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.telemetry import tracing
with tracing.span("work", who={who!r}):
    pass
tracing.instant("done")
# atexit dumps into DMLC_TRACE_DIR (how submit-run processes leave
# their trace files behind)
"""


def test_merge_round_trips_a_two_process_run(tmp_path):
    """Two REAL processes dump traces (atexit + DMLC_TRACE_DIR); the
    ``tools trace merge`` CLI joins them into one loadable timeline
    with both processes distinguishable."""
    env = {
        **os.environ, "DMLC_TRACE_DIR": str(tmp_path), "DMLC_TRACE": "on",
    }
    for who, rank in (("alpha", "0"), ("beta", "1")):
        proc_env = {
            **env, "DMLC_ROLE": "worker", "DMLC_TASK_ID": rank,
        }
        out = subprocess.run(
            [sys.executable, "-c",
             _PROC_SNIPPET.format(repo=REPO, who=who)],
            capture_output=True, text=True, env=proc_env, timeout=60,
        )
        assert out.returncode == 0, out.stderr
    files = sorted(
        str(tmp_path / f)
        for f in os.listdir(tmp_path)
        if f.startswith("dmlc-trace-")
    )
    assert len(files) == 2
    from dmlc_core_tpu import tools

    merged_path = str(tmp_path / "merged.json")
    rc = tools.main(["trace", "merge"] + files + ["-o", merged_path])
    assert rc == 0
    merged = tracing.load_trace(merged_path)
    assert merged["otherData"]["merged"] == 2
    pids = {
        e["pid"] for e in merged["traceEvents"] if e["ph"] == "X"
    }
    assert len(pids) == 2  # two processes, distinct rows
    labels = {
        e["args"]["name"]
        for e in merged["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert any("worker0" in lb for lb in labels)
    assert any("worker1" in lb for lb in labels)
    whos = {
        e["args"]["who"]
        for e in merged["traceEvents"]
        if e["ph"] == "X" and e["name"] == "work"
    }
    assert whos == {"alpha", "beta"}
    # events stay time-sorted after the merge
    ts = [e["ts"] for e in merged["traceEvents"] if "ts" in e]
    assert ts == sorted(ts)


def test_merge_remaps_colliding_pids(fresh, tmp_path):
    with tracing.span("dup"):
        pass
    p = tracing.dump(str(tmp_path / "a.json"))
    merged = tracing.merge_traces([p, p])  # same pid twice
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert len(pids) == 2  # the collision got a synthetic pid


# -- stall attribution ---------------------------------------------------------


def _fake_trace():
    """Synthetic timeline: a transfer thread doing 3 x 10ms of pack
    work with one 50ms host_pull stall, a consumer with a 20ms
    transfer_wait — known numbers for the report to recover."""
    pid = 7
    mk = lambda name, tid, ts_ms, dur_ms: {
        "ph": "X", "name": name, "pid": pid, "tid": tid,
        "ts": ts_ms * 1000.0, "dur": dur_ms * 1000.0,
    }
    events = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": "worker0 (pid 7)"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 1,
         "args": {"name": "staging-xfer"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 2,
         "args": {"name": "MainThread"}},
        mk("dmlc:dispatch_pack", 1, 0, 10),
        mk("dmlc:host_pull", 1, 10, 50),   # the starvation gap
        mk("dmlc:dispatch_pack", 1, 60, 10),
        mk("dmlc:dispatch_pack", 1, 70, 10),
        mk("dmlc:transfer_wait", 2, 5, 20),
    ]
    return {"traceEvents": events}


def test_stall_report_attributes_busy_and_stalls():
    rep = tracing.stall_report(_fake_trace(), gap_ms=25.0)
    assert rep["busy_seconds_by_stage"] == {"dispatch_pack": 0.03}
    assert rep["stall_seconds_by_stage"] == {
        "host_pull": 0.05, "transfer_wait": 0.02,
    }
    # exactly one gap clears the 25ms threshold, quantified
    (gap,) = rep["starvation_gaps"]
    assert gap["stage"] == "host_pull"
    assert gap["duration_ms"] == 50.0
    assert gap["thread"] == "staging-xfer"
    # thread rollup: xfer thread busy 80ms over an 80ms extent
    xfer = rep["threads"]["worker0 (pid 7)/staging-xfer"]
    assert xfer["busy_seconds"] == pytest.approx(0.08)
    assert xfer["idle_seconds"] == pytest.approx(0.0)
    # critical path lands on the busiest thread with the stall visible
    crit = rep["critical_path"]["worker0 (pid 7)"]
    assert crit["bottleneck_thread"] == "staging-xfer"
    assert crit["attributed_seconds"]["host_pull"] == 0.05


def test_report_cli_prints_busy_idle_and_gaps(tmp_path, capsys):
    from dmlc_core_tpu import tools

    path = str(tmp_path / "t.json")
    tracing.write_trace(_fake_trace(), path)
    rc = tools.main(["trace", "report", path, "--gap-ms", "25"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "host_pull" in out and "stall" in out
    assert "dispatch_pack" in out and "busy" in out
    assert "starvation gaps >= 25.0 ms: 1" in out
    assert "50.00 ms" in out
    assert "critical-path" in out


def test_union_seconds_handles_nesting():
    # nested + overlapping intervals must not double count
    assert tracing._union_seconds(
        [(0.0, 100.0), (10.0, 50.0), (90.0, 150.0)]
    ) == pytest.approx(150.0 / 1e6)


# -- instrumented layers feed the ring -----------------------------------------


def test_windowed_drain_leaves_spans_on_the_ring(fresh, tmp_path):
    """The split layer's instrumentation end-to-end: a compressed
    windowed drain records window loads, refills and decode spans."""
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io import split as io_split
    from dmlc_core_tpu.io.stream import FileStream

    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.rec.idx")
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(f, fi, codec="zlib", block_bytes=1024)
        for i in range(400):
            w.write_record(f"record-{i:06d}".encode() * 4)
        w.flush_block()
    sp = io_split.create(
        f"{rec}?index={idx}&shuffle=record&window=100",
        type="recordio", threaded=False,
    )
    rows = 0
    while True:
        g = sp.next_gather_batch(64)
        if g is None:
            break
        rows += len(g[1])
    sp.close()
    assert rows == 400
    names = {e["name"] for e in _span_events(tracing.to_chrome_trace())}
    assert "dmlc:window_load" in names
    assert "dmlc:gather_refill" in names
    assert "dmlc:window_span_decode" in names
    assert "dmlc:decode_block" in names


def test_retry_backoff_spans_recorded(fresh):
    from dmlc_core_tpu.io.retry import RetryPolicy

    pol = RetryPolicy(base_secs=0.001, cap_secs=0.002, sleep=lambda s: None)
    pol.pause(what="GET s3://bucket/key")
    spans = _span_events(tracing.to_chrome_trace())
    assert [s["name"] for s in spans] == ["dmlc:retry_backoff"]
    assert spans[0]["args"]["what"] == "GET s3://bucket/key"
    assert spans[0]["args"]["delay_ms"] > 0


# -- the dmlc-submit acceptance path -------------------------------------------

_SUBMIT_WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.tracker.client import RabitWorker
from dmlc_core_tpu.io import split as io_split
w = RabitWorker()
rank = w.start()
sp = io_split.create(
    {rec!r} + "?index=" + {idx!r} + "&shuffle=record&window=128",
    type="recordio", threaded=False)
rows = 0
while True:
    g = sp.next_gather_batch(64)
    if g is None:
        break
    rows += len(g[1])
sp.close()
assert rows == 500, rows
w.shutdown()
"""


@pytest.mark.blockcache
def test_submit_run_merges_workers_daemon_and_tracker(tmp_path):
    """ISSUE 8 acceptance: a ``dmlc-submit --block-cache`` run with 2
    workers leaves per-process trace files behind that ``tools trace
    merge`` joins into one Perfetto-loadable timeline containing spans
    from the worker pids, the cache daemon AND the tracker; ``tools
    trace report`` prints per-stage busy/idle plus a quantified
    starvation gap."""
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream

    rec = str(tmp_path / "corpus.rec")
    idx = rec + ".idx"
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        w = IndexedRecordIOWriter(f, fi, codec="zlib", block_bytes=2048)
        for i in range(500):
            w.write_record(f"row-{i:06d}|".encode() * 8)
        w.flush_block()
    trace_dir = tmp_path / "traces"
    script = tmp_path / "worker.py"
    script.write_text(_SUBMIT_WORKER.format(repo=REPO, rec=rec, idx=idx))
    out = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.tracker.submit",
         "--cluster", "local", "--num-workers", "2",
         "--host-ip", "127.0.0.1", "--block-cache",
         "--trace-dir", str(trace_dir),
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "DMLC_TRACE": "on", "JAX_PLATFORMS": "cpu"},
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    files = sorted(
        str(trace_dir / f)
        for f in os.listdir(trace_dir)
        if f.startswith("dmlc-trace-")
    )
    # 2 workers + the cache daemon + the tracker(submit) process
    assert len(files) >= 4, files
    from dmlc_core_tpu import tools

    merged_path = str(tmp_path / "job.json")
    rc = tools.main(["trace", "merge"] + files + ["-o", merged_path])
    assert rc == 0
    merged = tracing.load_trace(merged_path)
    labels = {
        e["args"]["name"]
        for e in merged["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert any("worker0" in lb for lb in labels), labels
    assert any("worker1" in lb for lb in labels), labels
    assert any("blockcache-daemon" in lb for lb in labels), labels
    assert any("tracker" in lb for lb in labels), labels
    names = {
        e["name"] for e in merged["traceEvents"] if e["ph"] == "X"
    }
    assert "dmlc:window_load" in names          # worker spans
    assert any(n.startswith("dmlc:blockcache_") for n in names), names
    instants = {
        e["name"] for e in merged["traceEvents"] if e["ph"] == "i"
    }
    assert "dmlc:tracker_start" in instants      # tracker events
    assert "dmlc:tracker_rank_assigned" in instants
    # the report over the merged run: per-stage busy/idle + >=1 gap
    rep = tracing.stall_report(
        tracing.load_trace(merged_path), gap_ms=0.05
    )
    assert rep["busy_seconds_by_stage"], rep
    assert rep["threads"]
    assert len(rep["starvation_gaps"]) >= 1, rep


# -- causal RPC trace context (ISSUE 14) ---------------------------------------


def test_trace_context_roundtrip_and_malformed(fresh):
    ctx = tracing.rpc_context()
    dec = tracing.decode_context(ctx)
    assert dec is not None and dec[0] > 0 and dec[1] > 0
    assert tracing.encode_context(*dec) == ctx
    # malformed contexts cost the arrow, never an exception
    for bad in (None, "", "zz", "123", "a" * 33, "g" * 16 + "-" + "f" * 16,
                42, b"x"):
        assert tracing.decode_context(bad) is None
        tracing.handler_flow(bad)  # no-op, no raise


def test_flow_events_bind_wait_span_to_handler_span(fresh):
    """The export contract Perfetto needs: the client's "s" flow is
    temporally inside its wait span, the server's "f" (same id, same
    cat, bp=e) inside the handler span."""
    with tracing.span("dmlc:lookup_wait"):
        ctx = tracing.rpc_context()
    with tracing.handler_span("dmlc:lookup_lookup", ctx):
        time.sleep(0.001)
    trace = tracing.to_chrome_trace()
    evs = trace["traceEvents"]
    starts = [e for e in evs if e["ph"] == "s"]
    ends = [e for e in evs if e["ph"] == "f"]
    assert len(starts) == 1 and len(ends) == 1
    s, f = starts[0], ends[0]
    assert s["id"] == f["id"] and s["cat"] == f["cat"] == "dmlc.flow"
    assert s["name"] == f["name"]
    assert f["bp"] == "e"
    wait = next(e for e in evs if e.get("name") == "dmlc:lookup_wait")
    handler = next(
        e for e in evs if e.get("name") == "dmlc:lookup_lookup"
    )
    assert wait["ts"] <= s["ts"] <= wait["ts"] + wait["dur"]
    assert handler["ts"] <= f["ts"] <= handler["ts"] + handler["dur"]
    # the handler span records the context for grep-ability
    assert handler["args"]["tc"] == ctx
    # and the flow id IS the context's span id
    assert int(s["id"], 16) == tracing.decode_context(ctx)[1]


def test_binary_flow_ids_for_frame_protocols(fresh):
    """The collective's DCL1 header carries the raw 64-bit id."""
    with tracing.span("send_side"):
        fid = tracing.flow_send_id()
    assert fid > 0
    with tracing.span("dmlc:allreduce_wait"):
        tracing.flow_recv(fid)
    tracing.flow_recv(0)  # recorder-off sender: no event, no raise
    evs = tracing.to_chrome_trace()["traceEvents"]
    assert [e["ph"] for e in evs if e["ph"] in "sf"] == ["s", "f"]
    s, f = (e for e in evs if e["ph"] in "sf")
    assert s["id"] == f["id"] == f"{fid:x}"


def test_rpc_context_none_when_disabled(fresh):
    tracing.set_enabled(False)
    assert tracing.rpc_context() is None
    assert tracing.flow_send_id() == 0


def test_wait_spans_mirror_into_stall_counters(fresh):
    """Completed wait-stage spans tick trace.stall_seconds{stage=} —
    the registry mirror the windowed stall-fraction query reads."""
    from dmlc_core_tpu.telemetry import default_registry

    key = 'trace.stall_seconds{stage="shard_lease_wait"}'
    before = default_registry().counter_values(names=[key]).get(key, 0.0)
    with tracing.span("dmlc:shard_lease_wait"):
        time.sleep(0.01)
    with tracing.span("dmlc:window_load"):  # busy stage: NOT mirrored
        time.sleep(0.001)
    after = default_registry().counter_values(names=[key])[key]
    assert after - before >= 0.009
    busy = default_registry().counter_values(
        names=['trace.stall_seconds{stage="window_load"}']
    )
    assert not busy


def test_clock_offset_recorded_and_merge_aligns(fresh, tmp_path):
    tracing.set_clock_offset(2_000_000.0)  # this process runs 2ms fast
    tracing.instant("dmlc:mark")
    trace = tracing.to_chrome_trace()
    assert trace["otherData"]["clock_offset_ns"] == 2_000_000.0
    assert trace["otherData"]["clock_offset_source"] == "heartbeat_rtt"
    raw_ts = next(
        e["ts"] for e in trace["traceEvents"] if e["ph"] == "i"
    )
    # default merge: timestamps untouched (same-host runs)
    merged = tracing.merge_traces([trace])
    assert any(
        e.get("ts") == raw_ts for e in merged["traceEvents"]
    )
    # align_clocks subtracts the offset (ns -> us)
    aligned = tracing.merge_traces([trace], align_clocks=True)
    shifted = next(
        e["ts"] for e in aligned["traceEvents"] if e["ph"] == "i"
    )
    assert shifted == pytest.approx(raw_ts - 2000.0)
    # per-file otherData (offset included) is preserved for forensics
    assert aligned["otherData"]["processes"][0]["clock_offset_ns"] == (
        2_000_000.0
    )
