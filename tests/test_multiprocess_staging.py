"""Multi-process staging: the jax.distributed branch of stage_batch.

Covers `jax.make_array_from_process_local_data` (staging/pipeline.py) —
the path every sharding test elsewhere skips because the suite runs one
process over 8 virtual devices. Here two REAL processes each stage their
(part_index, num_parts) = process_shard() slice of a rowrec shard into a
global mesh-sharded batch, and a jitted global reduction proves every
row landed exactly once (the reference's rank-parameterized distributed
split test — unittest_inputsplit.cc:116-145 — lifted from threads to
processes).

Marked slow: two fresh jax imports + a distributed CPU handshake.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ROWS = 512          # divisible by 2 parts x B_LOCAL
B_LOCAL = 128         # per-process batch rows; global batch = 256 over 8 dev
K = 7                 # uniform nnz per row -> byte-split lands on a record


WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
import jax

# the axon TPU plugin force-registers itself and wins over JAX_PLATFORMS
# alone (see tests/conftest.py); the config pin must precede any backend
# or distributed initialization
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address={coord!r},
    num_processes=2,
    process_id={pid},
)
import numpy as np
from dmlc_core_tpu.parallel.mesh import make_mesh, process_shard
from dmlc_core_tpu.staging import BatchSpec, StagingPipeline, ell_batches

part, nparts = process_shard()
assert (part, nparts) == ({pid}, 2), (part, nparts)

mesh = make_mesh(axis_names=("data",))  # all 8 global devices
spec = BatchSpec(batch_size={b_local}, layout="ell", max_nnz={k})
stream = ell_batches({rec!r}, spec, part_index=part, num_parts=nparts)
pipe = StagingPipeline(stream, mesh=mesh)

total = 0.0
rows = 0
weights_sum = 0.0
for dev in pipe:
    g = dev["labels"]
    assert g.shape == ({b_local} * 2,), g.shape          # GLOBAL batch
    assert len(g.sharding.device_set) == 8               # spans the mesh
    total += float(jax.jit(lambda a: a.sum())(g))
    weights_sum += float(jax.jit(lambda a: a.sum())(dev["weights"]))
    rows += g.shape[0]
stream.close()
pipe.close()
with open({out!r} + str({pid}), "w") as f:
    f.write("%r %r %r" % (total, weights_sum, rows))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_staging_exact_cover(tmp_path):
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import write_rowrec
    from dmlc_core_tpu.io.stream import FileStream

    # labels = row ids -> the global sum is a unique fingerprint of
    # "every row exactly once"
    n = N_ROWS
    offset = np.arange(n + 1, dtype=np.int64) * K
    rng = np.random.default_rng(0)
    blk = RowBlock(
        offset=offset,
        label=np.arange(n, dtype=np.float32),
        index=rng.integers(0, 1000, n * K).astype(np.uint32),
        value=rng.normal(size=n * K).astype(np.float32),
    )
    rec = str(tmp_path / "mp.rec")
    with FileStream(rec, "w") as f:
        write_rowrec(f, [blk])

    coord = f"127.0.0.1:{_free_port()}"
    out = str(tmp_path / "proc")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=4"]
    )
    procs = []
    for pid in range(2):
        script = tmp_path / f"w{pid}.py"
        script.write_text(
            textwrap.dedent(
                WORKER.format(
                    repo=REPO, coord=coord, pid=pid, rec=rec,
                    b_local=B_LOCAL, k=K, out=out,
                )
            )
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    try:
        outs = [p.communicate(timeout=300) for p in procs]
    finally:
        # a worker dying early leaves its peer wedged in the collective;
        # communicate(timeout=...) does NOT kill on timeout — do it here
        # so neither process leaks holding the coordinator port
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, (o, e) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o}\n{e}"

    expected_label_sum = float(n * (n - 1) / 2)
    for pid in range(2):
        total, weights_sum, rows = open(out + str(pid)).read().split()
        # both processes observed the same GLOBAL batches: every row
        # exactly once (label sum is the arange fingerprint), no padding
        # rows counted as real (weights sum == n)
        assert float(total) == expected_label_sum
        assert float(weights_sum) == float(n)
        assert int(rows) == n
