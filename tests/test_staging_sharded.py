"""Packed-shard mesh staging (ISSUE 3 tentpole): the coalesced
one-DMA-per-device path must be BIT-IDENTICAL to the per-array
``NamedSharding`` path for every parser family — dense libsvm, csv,
rowrec ELL, libfm ELL — including padded tail batches where
``ntotal % world != 0``. Plus the satellites that guard it: the
unpacker-cache LRU, the non-contiguous-view layout rejection, and the
usable-CPU autodetect the parse pools size from.

Runs on the virtual 8-device CPU mesh (conftest sets
XLA_FLAGS/JAX_PLATFORMS).
"""

import os

import numpy as np
import pytest

from dmlc_core_tpu.staging import (
    Batch,
    BatchSpec,
    FixedShapeBatcher,
    StagingPipeline,
    StagingStats,
    dense_batches,
    drain_close,
    ell_batches,
    stage_batch,
)
from dmlc_core_tpu.staging.pipeline import (
    _packed_layout,
    _stage_per_array_mesh,
    unpack_cache_stats,
)

pytestmark = pytest.mark.jax

# 16 rows/batch over a 4-way data axis → 4 rows per shard; N_ROWS=41
# leaves a 9-valid-row padded tail batch (41 % 16 = 9, and 41 is odd
# against every world size in play — the ntotal % world != 0 case)
BATCH_ROWS = 16
N_ROWS = 41


def _mesh(shape=(4, 2), axes=("data", "model")):
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devices, axes)


def _write_libsvm(path, rng):
    with open(path, "w") as f:
        for i in range(N_ROWS):
            feats = " ".join(
                f"{j}:{rng.normal():.6f}" for j in range(6)
            )
            f.write(f"{i % 2} {feats}\n")


def _write_csv(path, rng):
    with open(path, "w") as f:
        for i in range(N_ROWS):
            f.write(
                "%d,%s\n"
                % (i % 2, ",".join(f"{rng.normal():.6f}" for _ in range(6)))
            )


def _write_libfm(path, rng):
    with open(path, "w") as f:
        for i in range(N_ROWS):
            toks = " ".join(
                f"{j}:{j * 3 + 1}:{rng.uniform():.6f}" for j in range(4)
            )
            f.write(f"{i % 2} {toks}\n")


def _write_rowrec(path, rng):
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import write_rowrec
    from dmlc_core_tpu.io.stream import FileStream

    k = 4
    offset = np.arange(N_ROWS + 1, dtype=np.int64) * k
    blk = RowBlock(
        offset=offset,
        label=(np.arange(N_ROWS) % 2).astype(np.float32),
        index=rng.integers(0, 32, N_ROWS * k).astype(np.uint32),
        value=rng.normal(size=N_ROWS * k).astype(np.float32),
    )
    with FileStream(path, "w") as f:
        write_rowrec(f, [blk])


def _streams(tmp_path, value_dtype=np.float32):
    """One (name, batch stream) per parser family; every batch carries
    ``packed`` whichever producer (fused native or generic) serves it."""
    rng = np.random.default_rng(5)
    out = []
    dense_spec = BatchSpec(
        batch_size=BATCH_ROWS, layout="dense", num_features=8,
        value_dtype=np.dtype(value_dtype),
    )
    ell_spec = BatchSpec(
        batch_size=BATCH_ROWS, layout="ell", max_nnz=4,
        value_dtype=np.dtype(value_dtype),
    )
    p = tmp_path / "g.libsvm"
    _write_libsvm(p, rng)
    out.append(("libsvm_dense", dense_batches(str(p), dense_spec)))
    p = tmp_path / "g.csv"
    _write_csv(p, rng)
    out.append(
        (
            "csv_dense",
            dense_batches(str(p) + "?format=csv&label_column=0", dense_spec),
        )
    )
    p = tmp_path / "g.rec"
    _write_rowrec(p, rng)
    out.append(("rowrec_ell", ell_batches(str(p), ell_spec)))
    p = tmp_path / "g.libfm"
    _write_libfm(p, rng)
    out.append(
        ("libfm_ell", ell_batches(str(p) + "?format=libfm", ell_spec))
    )
    return out


@pytest.mark.parametrize("mesh_shape,axes", [
    ((4, 2), ("data", "model")),   # the dryrun's 2-D dp×tp mesh
    ((8,), ("data",)),             # plain 8-way data parallel
])
def test_packed_shard_golden_equivalence(tmp_path, mesh_shape, axes):
    """Every parser family, every batch (padded tail included): the
    packed-shard path must produce bit-identical device values AND
    identical shardings to the per-array NamedSharding path."""
    mesh = _mesh(mesh_shape, axes)
    for name, stream in _streams(tmp_path):
        n_batches = 0
        rows = 0
        for batch in stream:
            assert batch.packed is not None, name
            stats = StagingStats()
            dev = stage_batch(batch, mesh=mesh, data_axis="data",
                              stats=stats)
            assert stats.packed_shard_dma is True, name
            # ONE u8 put per addressable device, never per array
            assert stats.device_puts == len(mesh.devices.flat), name
            ref = _stage_per_array_mesh(batch, mesh, "data", None)
            assert set(dev) == set(ref), name
            for k in ref:
                assert dev[k].dtype == ref[k].dtype, (name, k)
                assert dev[k].shape == ref[k].shape, (name, k)
                assert dev[k].sharding == ref[k].sharding, (name, k)
                np.testing.assert_array_equal(
                    np.asarray(dev[k]), np.asarray(ref[k]), err_msg=f"{name}:{k}"
                )
            n_batches += 1
            rows += batch.n_valid
        stream.close()
        assert rows == N_ROWS, name
        # 41 rows / 16-row batches → 3 batches, last one padded
        assert n_batches == 3, name


def test_generic_batcher_packs_and_matches_per_array(tmp_path):
    """The generic FixedShapeBatcher output (no native kernels in the
    loop at all) rides the packed-shard path too — f16 values included
    (odd itemsize against the 8-byte section alignment)."""
    from dmlc_core_tpu.data.row_block import RowBlock

    mesh = _mesh((8,), ("data",))
    spec = BatchSpec(
        batch_size=BATCH_ROWS, layout="ell", max_nnz=3,
        value_dtype=np.dtype(np.float16),
    )
    b = FixedShapeBatcher(spec)
    sizes = [2] * 19  # 19 rows → one full batch + padded tail of 3
    offset = np.zeros(len(sizes) + 1, np.int64)
    np.cumsum(sizes, out=offset[1:])
    blk = RowBlock(
        offset=offset,
        label=np.arange(len(sizes), dtype=np.float32),
        index=(np.arange(int(offset[-1]), dtype=np.uint64) % 16),
        value=np.linspace(1, 2, int(offset[-1]), dtype=np.float32),
    )
    batches = list(b.batches(iter([blk])))
    assert [x.n_valid for x in batches] == [16, 3]
    for batch in batches:
        assert batch.packed is not None
        dev = stage_batch(batch, mesh=mesh, data_axis="data")
        ref = _stage_per_array_mesh(batch, mesh, "data", None)
        for k in ref:
            assert dev[k].sharding == ref[k].sharding, k
            np.testing.assert_array_equal(
                np.asarray(dev[k]), np.asarray(ref[k]), err_msg=k
            )


def test_pipeline_mesh_packed_shard_stats(tmp_path):
    """End-to-end through StagingPipeline: the dispatch ring stages a
    mesh stream via the packed-shard path and the counters say so."""
    rng = np.random.default_rng(9)
    p = tmp_path / "p.rec"
    _write_rowrec(p, rng)
    spec = BatchSpec(batch_size=BATCH_ROWS, layout="ell", max_nnz=4)
    stream = ell_batches(str(p), spec)
    mesh = _mesh((4, 2), ("data", "model"))
    pipe = StagingPipeline(stream, mesh=mesh, data_axis="data")
    labels = []
    for dev in pipe:
        w = np.asarray(dev["weights"])
        labels.extend(np.asarray(dev["labels"])[w > 0].tolist())
    assert len(labels) == N_ROWS
    st = pipe.staging_stats()
    assert st["packed_shard_dma"] is True
    assert st["packed_shard_batches"] == 3
    assert st["per_array_batches"] == 0
    assert st["device_puts"] == 3 * 8
    assert st["dispatch_ring_depth"] >= 1
    assert pipe.io_stats()["staging"]["packed_shard_dma"] is True
    secs = pipe.stage_seconds
    assert secs["stage_dispatch"] == pytest.approx(
        secs["dispatch_pack"] + secs["dispatch_put"]
    )
    drain_close(pipe, stream)


def test_shard_unpacker_compiles_collective_free(tmp_path):
    """The per-shard unpack must contain ZERO collectives: ring workers
    execute unpacks concurrently, and on backends with rendezvous-based
    collectives two concurrent collective computations deadlock (seen
    live on the CPU backend before the shard_map rewrite — a plain jit
    with pinned shardings let GSPMD insert an all-gather for the
    shard-splitting reshape)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.staging.pipeline import _shard_plan, _shard_unpacker

    rng = np.random.default_rng(3)
    p = tmp_path / "c.rec"
    _write_rowrec(p, rng)
    spec = BatchSpec(
        batch_size=BATCH_ROWS, layout="ell", max_nnz=4,
        value_dtype=np.dtype(np.float16),
    )
    stream = ell_batches(str(p), spec)
    batch = next(iter(stream))
    mesh = _mesh((4, 2), ("data", "model"))
    entries, stride, n_shards = _shard_plan(batch, mesh, "data")
    fn = _shard_unpacker(entries, stride, mesh, "data", "cpu")
    aval = jax.ShapeDtypeStruct(
        (n_shards * stride,), np.uint8,
        sharding=NamedSharding(mesh, P("data")),
    )
    hlo = fn.lower(aval).compile().as_text()
    for op in ("all-gather", "all-reduce", "collective-permute",
               "all-to-all"):
        assert op not in hlo, f"unpacker compiled a {op}"
    stream.close()


def test_non_divisible_batch_falls_back_per_array():
    """batch_size % n_shards != 0 can't ride the packed-shard path; the
    plan must reject it (the per-array path then fails the same way a
    direct NamedSharding put would — that contract is unchanged)."""
    from dmlc_core_tpu.staging.pipeline import _shard_plan

    mesh = _mesh((4, 2), ("data", "model"))
    spec = BatchSpec(batch_size=6, layout="dense", num_features=4)
    b = FixedShapeBatcher(spec)
    from dmlc_core_tpu.data.row_block import RowBlock

    blk = RowBlock(
        offset=np.arange(7, dtype=np.int64),
        label=np.zeros(6, np.float32),
        index=np.arange(6, dtype=np.uint64) % 4,
        value=np.ones(6, np.float32),
    )
    (batch,) = list(b.push(blk))
    assert batch.packed is not None
    assert _shard_plan(batch, mesh, "data") is None
    # unknown data axis also refuses (falls back instead of KeyError)
    assert _shard_plan(batch, mesh, "nope") is None


# -- satellite: _packed_layout contiguity guard ------------------------------


def _manual_packed_batch(reverse_labels=False):
    """Dense Batch whose arrays are hand-built views into one buffer;
    optionally with a reversed (negative-stride) labels view whose
    byte_bounds still lie inside the buffer."""
    nb = 16 * 2 * 4 + 16 * 4 + 16 * 4  # x[16,2] f32 | labels | weights
    buf = np.zeros(nb, dtype=np.uint8)
    x = buf[: 16 * 2 * 4].view(np.float32).reshape(16, 2)
    labels = buf[16 * 2 * 4 : 16 * 2 * 4 + 16 * 4].view(np.float32)
    weights = buf[16 * 2 * 4 + 16 * 4 :].view(np.float32)
    x[:] = np.arange(32).reshape(16, 2)
    labels[:] = np.arange(16)
    weights[:] = 1.0
    if reverse_labels:
        labels = labels[::-1]
    return Batch(labels=labels, weights=weights, n_valid=16, x=x,
                 packed=buf)


def test_packed_layout_rejects_negative_stride_views():
    """byte_bounds passes for a reversed view whose bytes are NOT the
    dense run [off, off+nbytes) — bitcasting it would stage garbage.
    The layout derivation must reject and force the per-array path."""
    good = _manual_packed_batch()
    assert _packed_layout(good) is not None
    bad = _manual_packed_batch(reverse_labels=True)
    assert not bad.labels.flags.c_contiguous
    assert _packed_layout(bad) is None


def test_packed_layout_rejects_noncontiguous_packed():
    batch = _manual_packed_batch()
    object.__setattr__(batch, "packed", batch.packed[::-1])
    assert _packed_layout(batch) is None


def test_packed_layout_accepts_dense_views():
    layout = _packed_layout(_manual_packed_batch())
    assert layout is not None
    assert {e[0] for e in layout} == {"x", "labels", "weights"}


def test_strided_view_batch_still_stages_correctly():
    """A batch whose arrays are NOT dense views (sliced with a step)
    must stage through the per-array path with correct values."""
    bad = _manual_packed_batch(reverse_labels=True)
    dev = stage_batch(bad)
    np.testing.assert_array_equal(
        np.asarray(dev["labels"]), bad.labels
    )


# -- satellite: unpacker-cache LRU -------------------------------------------


def test_unpack_cache_lru_bounds_and_evicts(monkeypatch, tmp_path):
    monkeypatch.setenv("DMLC_UNPACK_CACHE", "2")
    before = unpack_cache_stats()["unpack_cache_evictions"]
    # distinct layouts (distinct batch shapes) mint distinct unpackers
    for nf in (3, 5, 7, 9, 11):
        spec = BatchSpec(batch_size=8, layout="dense", num_features=nf)
        b = FixedShapeBatcher(spec)
        from dmlc_core_tpu.data.row_block import RowBlock

        blk = RowBlock(
            offset=np.arange(9, dtype=np.int64),
            label=np.zeros(8, np.float32),
            index=np.zeros(8, np.uint64),
            value=np.ones(8, np.float32),
        )
        (batch,) = list(b.push(blk))
        dev = stage_batch(batch)
        assert np.asarray(dev["x"]).shape == (8, nf)
    stats = unpack_cache_stats()
    assert stats["unpack_cache_capacity"] == 2
    assert stats["unpack_cache_size"] <= 2
    assert stats["unpack_cache_evictions"] >= before + 3
    # a re-staged layout still works after eviction (re-jits, same math)
    spec = BatchSpec(batch_size=8, layout="dense", num_features=3)
    b = FixedShapeBatcher(spec)
    from dmlc_core_tpu.data.row_block import RowBlock

    blk = RowBlock(
        offset=np.arange(9, dtype=np.int64),
        label=np.arange(8, dtype=np.float32),
        index=np.zeros(8, np.uint64),
        value=np.ones(8, np.float32),
    )
    (batch,) = list(b.push(blk))
    dev = stage_batch(batch)
    np.testing.assert_array_equal(
        np.asarray(dev["labels"]), np.arange(8, dtype=np.float32)
    )


# -- satellite: usable-CPU autodetect ----------------------------------------


def test_available_cpus_floor_and_cap():
    from dmlc_core_tpu.utils.cpus import available_cpus

    n = available_cpus()
    assert 1 <= n <= (os.cpu_count() or 1)


def test_parse_threads_env_override(monkeypatch):
    from dmlc_core_tpu.utils import cpus

    monkeypatch.setenv("DMLC_PARSE_THREADS", "3")
    assert cpus.parse_threads() == 3
    assert cpus.parse_threads(16) == 3
    monkeypatch.delenv("DMLC_PARSE_THREADS")
    # legacy alias honored here too, so the override is consistent
    # across every pool sized through parse_threads (bench, fused
    # fan-out, generic text parser)
    monkeypatch.setenv("DMLC_TPU_PARSER_THREADS", "5")
    assert cpus.parse_threads() == 5
    monkeypatch.delenv("DMLC_TPU_PARSER_THREADS")
    monkeypatch.setattr(cpus, "available_cpus", lambda: 4)
    assert cpus.parse_threads() == 4
    assert cpus.parse_threads(2) == 2
    assert cpus.parse_threads(99) == 4


def _pin_proc_cgroup(monkeypatch, tmp_path, text):
    from dmlc_core_tpu.utils import cpus

    proc = tmp_path / "proc_self_cgroup"
    proc.write_text(text)
    monkeypatch.setattr(cpus, "_PROC_SELF_CGROUP", str(proc))


def test_cgroup_quota_parsing(monkeypatch, tmp_path):
    from dmlc_core_tpu.utils import cpus

    _pin_proc_cgroup(monkeypatch, tmp_path, "0::/\n")
    v2 = tmp_path / "cpu.max"
    v2.write_text("150000 100000\n")
    monkeypatch.setattr(cpus, "_CGROUP_V2_CPU_MAX", str(v2))
    assert cpus.cgroup_cpu_quota() == pytest.approx(1.5)
    v2.write_text("max 100000\n")
    assert cpus.cgroup_cpu_quota() is None
    # v1 fallback when the v2 file is absent
    monkeypatch.setattr(cpus, "_CGROUP_V2_CPU_MAX", str(tmp_path / "nope"))
    q = tmp_path / "cpu.cfs_quota_us"
    p = tmp_path / "cpu.cfs_period_us"
    q.write_text("50000\n")
    p.write_text("100000\n")
    monkeypatch.setattr(cpus, "_CGROUP_V1_QUOTA", str(q))
    monkeypatch.setattr(cpus, "_CGROUP_V1_PERIOD", str(p))
    assert cpus.cgroup_cpu_quota() == pytest.approx(0.5)
    q.write_text("-1\n")
    assert cpus.cgroup_cpu_quota() is None


def test_cgroup_quota_found_in_own_nonroot_cgroup(monkeypatch, tmp_path):
    """Non-namespaced containers (docker --cgroupns=host, systemd
    CPUQuota slices): the quota lives at the PROCESS's cgroup path, not
    the root — /proc/self/cgroup must be consulted, and the effective
    limit is the min over the ancestor chain."""
    from dmlc_core_tpu.utils import cpus

    _pin_proc_cgroup(
        monkeypatch, tmp_path, "0::/kube.slice/pod7/container3\n"
    )
    root = tmp_path / "cg2"
    leaf = root / "kube.slice" / "pod7" / "container3"
    leaf.mkdir(parents=True)
    monkeypatch.setattr(cpus, "_CGROUP_V2_CPU_MAX", str(root / "cpu.max"))
    (leaf / "cpu.max").write_text("200000 100000\n")
    assert cpus.cgroup_cpu_quota() == pytest.approx(2.0)
    # a tighter ancestor quota wins (effective = min over the chain)
    (root / "kube.slice" / "cpu.max").write_text("50000 100000\n")
    assert cpus.cgroup_cpu_quota() == pytest.approx(0.5)
    # v1 hierarchy resolution too
    monkeypatch.setattr(cpus, "_CGROUP_V2_CPU_MAX", str(tmp_path / "no2"))
    _pin_proc_cgroup(
        monkeypatch, tmp_path,
        "4:cpu,cpuacct:/docker/abc\n0::/other\n",
    )
    v1root = tmp_path / "cg1"
    d = v1root / "docker" / "abc"
    d.mkdir(parents=True)
    (d / "cpu.cfs_quota_us").write_text("25000\n")
    (d / "cpu.cfs_period_us").write_text("100000\n")
    monkeypatch.setattr(
        cpus, "_CGROUP_V1_QUOTA", str(v1root / "cpu.cfs_quota_us")
    )
    monkeypatch.setattr(
        cpus, "_CGROUP_V1_PERIOD", str(v1root / "cpu.cfs_period_us")
    )
    assert cpus.cgroup_cpu_quota() == pytest.approx(0.25)


def test_fractional_quota_still_gets_one_thread(monkeypatch):
    from dmlc_core_tpu.utils import cpus

    monkeypatch.setattr(cpus, "cgroup_cpu_quota", lambda: 0.4)
    assert cpus.available_cpus() >= 1


def test_shuffled_gather_batches_ride_packed_shard_dma(tmp_path):
    """ISSUE 6 acceptance: shuffled batches (gather fast path) land on
    the packed-shard mesh path — packed_shard_dma latches True, one u8
    put per addressable device, zero per-array fallbacks — with device
    values bit-identical to the legacy per-record shuffle staged the
    same way."""
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import encode_rows

    rng = np.random.default_rng(13)
    k = 4
    blk = RowBlock(
        offset=np.arange(N_ROWS + 1, dtype=np.int64) * k,
        label=(np.arange(N_ROWS) % 2).astype(np.float32),
        index=rng.integers(0, 32, N_ROWS * k).astype(np.uint32),
        value=rng.normal(size=N_ROWS * k).astype(np.float32),
    )
    rec = str(tmp_path / "sh.rec")
    idx = str(tmp_path / "sh.idx")
    with FileStream(rec, "w") as d, FileStream(idx, "w") as i:
        w = IndexedRecordIOWriter(d, i)
        for payload in encode_rows(blk):
            w.write_record(payload)
    spec = BatchSpec(batch_size=BATCH_ROWS, layout="ell", max_nnz=k)
    mesh = _mesh((4, 2), ("data", "model"))

    def staged(sugar=""):
        stream = ell_batches(
            f"{rec}?index={idx}&shuffle=record&seed=3{sugar}", spec
        )
        pipe = StagingPipeline(stream, mesh=mesh, data_axis="data")
        out = [
            {kk: np.asarray(v) for kk, v in dev.items()} for dev in pipe
        ]
        st = pipe.staging_stats()
        io = pipe.io_stats()
        drain_close(pipe, stream)
        return out, st, io

    got, st, io = staged()
    assert st["packed_shard_dma"] is True
    assert st["per_array_batches"] == 0
    assert st["packed_shard_batches"] == 3
    assert st["device_puts"] == 3 * 8  # one u8 DMA per device per batch
    assert io.get("gather_batches", 0) > 0
    assert io.get("gather_fallback_batches") == 0
    ref, _st, _io = staged("&legacy_shuffle=1")
    assert len(got) == len(ref) == 3
    for a, b in zip(got, ref):
        assert set(a) == set(b)
        for key in b:
            np.testing.assert_array_equal(a[key], b[key], err_msg=key)
