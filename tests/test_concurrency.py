"""Tests for ThreadedIter / ThreadGroup.

Modeled on reference test/unittest/unittest_threaditer.cc,
unittest_threaditer_exc_handling.cc, unittest_thread_group.cc.
"""

import threading
import time

import pytest

from dmlc_core_tpu.concurrency import (
    ConcurrentBlockingQueue,
    ThreadGroup,
    ThreadedIter,
    TimerThread,
)
from dmlc_core_tpu.utils import Error


def test_threaded_iter_basic_and_restart():
    epochs = []

    def produce():
        epochs.append(1)
        yield from range(10)

    it = ThreadedIter(produce, max_capacity=2)
    assert list(it) == list(range(10))
    assert it.next() is None  # stays exhausted
    it.before_first()
    assert list(it) == list(range(10))
    assert len(epochs) == 2
    it.destroy()


def test_threaded_iter_producer_exception_propagates():
    # reference IntProducerNextExc pattern: throw on the last element
    def produce():
        yield 1
        yield 2
        raise Error("produce failed")

    it = ThreadedIter(produce)
    assert it.next() == 1
    assert it.next() == 2
    with pytest.raises(Error, match="produce failed"):
        it.next()
    it.destroy()


def test_threaded_iter_exception_in_first_item():
    def produce():
        raise ValueError("immediate")
        yield  # pragma: no cover

    it = ThreadedIter(produce)
    with pytest.raises(ValueError, match="immediate"):
        it.next()
    # restart after exception works (reference exc-handling test does this)
    ok = [False]

    def produce_ok():
        if ok[0]:
            yield 42
        else:
            ok[0] = True
            raise ValueError("first time fails")

    it2 = ThreadedIter(produce_ok)
    with pytest.raises(ValueError):
        it2.next()
    it2.before_first()
    assert it2.next() == 42
    it2.destroy()


def test_threaded_iter_destroy_with_blocked_producer():
    # producer blocks on the bounded queue; destroy must not hang
    def produce():
        yield from range(100000)

    it = ThreadedIter(produce, max_capacity=2)
    assert it.next() == 0
    it.destroy()  # would deadlock without kill-signal draining


def test_concurrent_blocking_queue_kill():
    q = ConcurrentBlockingQueue(maxsize=4)
    q.put(1)
    assert q.pop() == 1
    results = []

    def consumer():
        results.append(q.pop())  # blocks until kill

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    q.signal_for_kill()
    t.join(timeout=2)
    assert not t.is_alive() and results == [None]
    assert q.pop() is None  # killed queue stays killed


def test_thread_group_lifecycle():
    g = ThreadGroup()
    counter = {"n": 0}

    def worker():
        while not g.shutdown_requested.wait(0.01):
            counter["n"] += 1

    g.launch("w1", worker)
    g.launch("w2", worker)
    assert g.count() == 2
    with pytest.raises(Error, match="already running"):
        g.launch("w1", worker)
    time.sleep(0.05)
    g.request_shutdown_all()
    assert g.join_all(timeout=2)
    assert counter["n"] > 0
    assert g.count() == 0


def test_timer_thread_fires_periodically():
    hits = []
    with TimerThread(0.02, lambda: hits.append(1)):
        time.sleep(0.13)
    n = len(hits)
    assert n >= 3
    time.sleep(0.05)
    assert len(hits) == n  # stopped


def test_threaded_iter_destroy_wakes_blocked_consumer():
    """A consumer blocked in next() (empty queue, stalled producer) must
    observe destroy() promptly — a downstream pipeline stage's thread
    pulls this iterator and its own teardown would otherwise spin on
    join forever (the StagingPipeline close path)."""
    release = threading.Event()

    def produce():
        yield 1
        release.wait(30)  # stalled upstream

    it = ThreadedIter(produce, max_capacity=1)
    assert it.next() == 1
    got = {}

    def consume():
        got["item"] = it.next()  # blocks: queue empty, producer stalled

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    assert t.is_alive()  # really blocked
    it.destroy(timeout=1.0)
    t.join(timeout=5)
    assert not t.is_alive()
    assert got["item"] is None  # clean end-of-stream, not an exception
    release.set()


def test_threaded_iter_destroy_bounded_join_orphans_stalled_producer():
    """destroy(timeout=...) must return within the bound even when the
    producer thread is stuck in uninterruptible user code; the orphaned
    daemon exits at its next queue put (kill flag)."""
    release = threading.Event()

    def produce():
        yield 1
        release.wait(30)  # emulates a blocking read Python can't interrupt
        yield 2  # pragma: no cover — kill flag drops it at the put

    it = ThreadedIter(produce, max_capacity=1)
    assert it.next() == 1
    time.sleep(0.1)  # let the producer enter the stall
    t0 = time.monotonic()
    joined = it.destroy(timeout=0.5)
    assert time.monotonic() - t0 < 5.0
    assert joined is False  # orphaned, not joined — callers must defer
    #                         tearing down resources the thread may touch
    release.set()  # orphan wakes, sees kill, exits without producing


def test_threaded_iter_default_destroy_still_joins_fully():
    """Without a timeout, destroy() keeps the join-to-completion
    exclusivity restart sites rely on (CachedInputSplit.before_first
    reopens shared resources right after)."""
    done = []

    def produce():
        try:
            yield 1
            yield 2
        finally:
            time.sleep(0.3)  # slow cleanup in the producer
            done.append(True)

    it = ThreadedIter(produce, max_capacity=1)
    assert it.next() == 1
    assert it.destroy() is True  # no timeout: waits for the finally
    assert done == [True]
