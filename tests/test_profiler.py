"""utils/profiler.py (previously untested — ISSUE 4 satellite):
annotate() degrades to a no-op without jax, trace() fails loudly without
jax, and spans feed the profiler.span_seconds duration histograms when
enabled."""

import pytest

from dmlc_core_tpu.telemetry import default_registry
from dmlc_core_tpu.utils import profiler


@pytest.fixture
def no_jax(monkeypatch):
    """Simulate a jax-less environment (the resolved-profiler cache is
    module state; None means 'import failed')."""
    monkeypatch.setattr(profiler, "_PROF", None)


@pytest.fixture
def hist_off():
    """Leave histogram enablement as the test found it."""
    yield
    profiler.enable_histograms(None)


def test_annotate_is_noop_context_manager_without_jax(no_jax, hist_off):
    profiler.enable_histograms(False)
    cm = profiler.annotate("dmlc:test")
    with cm as inner:
        assert inner is None  # nullcontext yields None
    # reentrant: annotate() hands out fresh context managers
    with profiler.annotate("dmlc:test"):
        pass


def test_trace_raises_clean_runtime_error_without_jax(no_jax):
    with pytest.raises(RuntimeError, match="requires jax"):
        with profiler.trace("/tmp/nowhere"):
            pass


def test_annotate_feeds_duration_histograms_when_enabled(no_jax, hist_off):
    profiler.enable_histograms(True)
    key = 'profiler.span_seconds{span="dmlc:test_span"}'
    before = (
        default_registry()
        .snapshot()["histograms"]
        .get(key, {})
        .get("count", 0)
    )
    for _ in range(3):
        with profiler.annotate("dmlc:test_span"):
            pass
    snap = default_registry().snapshot()["histograms"][key]
    assert snap["count"] - before == 3
    assert snap["sum"] >= 0
    # disabled again: no further samples recorded
    profiler.enable_histograms(False)
    with profiler.annotate("dmlc:test_span"):
        pass
    snap2 = default_registry().snapshot()["histograms"][key]
    assert snap2["count"] - before == 3


def test_histograms_env_default(monkeypatch, hist_off):
    profiler.enable_histograms(None)
    monkeypatch.delenv("DMLC_PROFILE_HIST", raising=False)
    assert profiler.histograms_enabled() is False
    monkeypatch.setenv("DMLC_PROFILE_HIST", "1")
    assert profiler.histograms_enabled() is True
    monkeypatch.setenv("DMLC_PROFILE_HIST", "0")
    assert profiler.histograms_enabled() is False
    # explicit override beats the env
    profiler.enable_histograms(True)
    assert profiler.histograms_enabled() is True


@pytest.mark.jax
def test_annotate_with_jax_still_times_spans(hist_off):
    """With real jax present, annotate() wraps TraceAnnotation AND (when
    enabled) still observes the duration histogram."""
    pytest.importorskip("jax")
    profiler.enable_histograms(True)
    key = 'profiler.span_seconds{span="dmlc:jax_span"}'
    with profiler.annotate("dmlc:jax_span"):
        pass
    snap = default_registry().snapshot()["histograms"][key]
    assert snap["count"] >= 1


def test_span_memo_bounded_on_dynamic_names(no_jax, hist_off):
    """annotate(f'step_{i}') with histograms on must not grow the memo
    dict forever — past the cap, lookups fall through to the registry
    (whose cardinality cap collapses the series). Runs LAST: it
    saturates the default registry's profiler.span_seconds family on
    purpose, so span-key assertions must precede it."""
    profiler.enable_histograms(True)
    before = len(profiler._SPAN_HISTS)
    for i in range(profiler._SPAN_MEMO_CAP + 50):
        with profiler.annotate(f"dmlc:dyn_{i}"):
            pass
    assert len(profiler._SPAN_HISTS) <= profiler._SPAN_MEMO_CAP, before
