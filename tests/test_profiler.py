"""utils/profiler.py (previously untested — ISSUE 4 satellite):
annotate() degrades to a no-op without jax, trace() fails loudly without
jax, and spans feed the profiler.span_seconds duration histograms when
enabled."""

import pytest

from dmlc_core_tpu.telemetry import default_registry, tracing
from dmlc_core_tpu.utils import profiler


@pytest.fixture
def no_jax(monkeypatch):
    """Simulate a jax-less environment (the resolved-profiler cache is
    module state; None means 'import failed')."""
    monkeypatch.setattr(profiler, "_PROF", None)


@pytest.fixture
def hist_off():
    """Leave histogram enablement as the test found it."""
    yield
    profiler.enable_histograms(None)


@pytest.fixture
def trace_off():
    """Force the flight recorder off (it is on by default, and an
    enabled ring makes annotate() a recording span, not a no-op)."""
    tracing.set_enabled(False)
    yield
    tracing.set_enabled(None)


def test_annotate_is_noop_context_manager_without_jax(
    no_jax, hist_off, trace_off
):
    profiler.enable_histograms(False)
    cm = profiler.annotate("dmlc:test")
    with cm as inner:
        assert inner is None  # nullcontext yields None
    # reentrant: annotate() hands out fresh context managers
    with profiler.annotate("dmlc:test"):
        pass


def test_trace_raises_clean_runtime_error_without_jax(no_jax):
    with pytest.raises(RuntimeError, match="requires jax"):
        with profiler.trace("/tmp/nowhere"):
            pass


def test_annotate_feeds_duration_histograms_when_enabled(no_jax, hist_off):
    profiler.enable_histograms(True)
    key = 'profiler.span_seconds{span="dmlc:test_span"}'
    before = (
        default_registry()
        .snapshot()["histograms"]
        .get(key, {})
        .get("count", 0)
    )
    for _ in range(3):
        with profiler.annotate("dmlc:test_span"):
            pass
    snap = default_registry().snapshot()["histograms"][key]
    assert snap["count"] - before == 3
    assert snap["sum"] >= 0
    # disabled again: no further samples recorded
    profiler.enable_histograms(False)
    with profiler.annotate("dmlc:test_span"):
        pass
    snap2 = default_registry().snapshot()["histograms"][key]
    assert snap2["count"] - before == 3


def test_histograms_env_default(monkeypatch, hist_off):
    profiler.enable_histograms(None)
    monkeypatch.delenv("DMLC_PROFILE_HIST", raising=False)
    assert profiler.histograms_enabled() is False
    monkeypatch.setenv("DMLC_PROFILE_HIST", "1")
    assert profiler.histograms_enabled() is True
    monkeypatch.setenv("DMLC_PROFILE_HIST", "0")
    assert profiler.histograms_enabled() is False
    # explicit override beats the env
    profiler.enable_histograms(True)
    assert profiler.histograms_enabled() is True


@pytest.mark.jax
def test_annotate_with_jax_still_times_spans(hist_off):
    """With real jax present, annotate() wraps TraceAnnotation AND (when
    enabled) still observes the duration histogram."""
    pytest.importorskip("jax")
    profiler.enable_histograms(True)
    key = 'profiler.span_seconds{span="dmlc:jax_span"}'
    with profiler.annotate("dmlc:jax_span"):
        pass
    snap = default_registry().snapshot()["histograms"][key]
    assert snap["count"] >= 1


def test_span_memo_concurrent_first_annotate_race(no_jax, hist_off):
    """ISSUE 8 satellite: concurrent FIRST annotate() calls must not
    double-register a span name (last-writer-wins in the memo would
    hand different threads different histogram objects) nor mis-account
    the memo cap (racing check-then-set inserts past it). All threads
    must land their observations on ONE histogram per name."""
    import threading

    profiler.enable_histograms(True)
    profiler._SPAN_HISTS.clear()
    n_threads, n_names = 8, 16
    seen = [[None] * n_names for _ in range(n_threads)]
    gate = threading.Barrier(n_threads)

    def worker(slot):
        gate.wait()  # maximize first-annotate collisions
        for i in range(n_names):
            with profiler.annotate(f"dmlc:race_{i}"):
                pass
            seen[slot][i] = profiler._SPAN_HISTS.get(f"dmlc:race_{i}")

    threads = [
        threading.Thread(target=worker, args=(s,))
        for s in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # one memoized histogram per name, shared by every thread
    for i in range(n_names):
        hists = {id(seen[s][i]) for s in range(n_threads)}
        assert len(hists) == 1, f"name {i} double-registered"
    assert len(profiler._SPAN_HISTS) == n_names  # cap accounting exact
    # and every observation landed on that one series
    key = 'profiler.span_seconds{span="dmlc:race_0"}'
    snap = default_registry().snapshot()["histograms"][key]
    assert snap["count"] >= n_threads


def test_span_memo_bounded_on_dynamic_names(no_jax, hist_off):
    """annotate(f'step_{i}') with histograms on must not grow the memo
    dict forever — past the cap, lookups fall through to the registry
    (whose cardinality cap collapses the series). Runs LAST: it
    saturates the default registry's profiler.span_seconds family on
    purpose, so span-key assertions must precede it."""
    profiler.enable_histograms(True)
    before = len(profiler._SPAN_HISTS)
    for i in range(profiler._SPAN_MEMO_CAP + 50):
        with profiler.annotate(f"dmlc:dyn_{i}"):
            pass
    assert len(profiler._SPAN_HISTS) <= profiler._SPAN_MEMO_CAP, before
