"""Sharded / multi-process checkpointing (§5.4 on a pod).

The r3 verdict's top gap: a tp-sharded param tree (the dryrun's FM with
``v: P(None,'model')``) could not be checkpointed in a real multi-process
run because ``np.asarray`` on a non-addressable array crashes. These
tests pin the new story end to end:

- single-process: sharded layout round-trips and RESHARDS onto a
  different mesh at restore time;
- completeness: a .d directory without its manifest is invisible
  (torn checkpoints can never be 'latest');
- two REAL processes: train the dryrun FM config, checkpoint mid-run
  (each process writes its own replica-0 shards), restart, and the
  resumed loss trajectory matches the uninterrupted one bit-for-bit —
  the reference's rabit Checkpoint/LoadCheckpoint resume contract
  (SURVEY §5.4, reference include/dmlc/io.h:132-146 primitives).
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fm_params_on_mesh(mesh_shape, axis_names, rules):
    import jax
    from jax.sharding import NamedSharding

    from dmlc_core_tpu.models import FactorizationMachine
    from dmlc_core_tpu.parallel import make_mesh

    mesh = make_mesh(mesh_shape, axis_names)
    model = FactorizationMachine(64, 8)
    params = model.init(jax.random.PRNGKey(0))
    placed = {
        k: jax.device_put(v, NamedSharding(mesh, rules.get(k, P_empty())))
        for k, v in params.items()
    }
    return mesh, model, placed


def P_empty():
    from jax.sharding import PartitionSpec

    return PartitionSpec()


def test_sharded_roundtrip_reshards_onto_new_mesh(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.checkpoint import Checkpointer

    rules = {"v": P(None, "model")}
    mesh_a, _, params = _fm_params_on_mesh((4, 2), ("data", "model"), rules)

    ck = Checkpointer(str(tmp_path / "ck"), sharded=True)
    path = ck.save(7, params)
    assert path is not None and path.endswith(".d")
    assert ck.steps() == [7]

    # restore onto a DIFFERENT mesh: 2x4 instead of 4x2 — 'model' now
    # spans 4 devices, so every leaf must be re-placed, not re-loaded
    mesh_b, _, template = _fm_params_on_mesh((2, 4), ("data", "model"), rules)
    step, back = ck.restore(template=template)
    assert step == 7
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(back[k]), np.asarray(params[k])
        )
        assert back[k].sharding == template[k].sharding, k
    # v really is sharded over the new model axis (4-way on dim 1)
    vshard = back["v"].addressable_shards[0]
    assert np.asarray(vshard.data).shape[1] == params["v"].shape[1] // 4


def test_sharded_without_template_returns_host(tmp_path):
    from jax.sharding import PartitionSpec as P

    from dmlc_core_tpu.checkpoint import Checkpointer

    rules = {"v": P(None, "model")}
    _, _, params = _fm_params_on_mesh((4, 2), ("data", "model"), rules)
    ck = Checkpointer(str(tmp_path / "ck"), sharded=True)
    ck.save(1, {"params": params, "step": 1, "note": "meta"})
    step, back = ck.restore()
    assert step == 1 and back["note"] == "meta" and back["step"] == 1
    assert isinstance(back["params"]["v"], np.ndarray)
    np.testing.assert_array_equal(
        back["params"]["v"], np.asarray(params["v"])
    )


def test_torn_sharded_checkpoint_is_invisible(tmp_path):
    from dmlc_core_tpu.checkpoint import Checkpointer, save_pytree

    base = tmp_path / "ck"
    ck = Checkpointer(str(base), process_index=0)
    ck.save(3, {"w": np.ones(4, np.float32)})  # legacy complete ckpt
    # a torn sharded checkpoint: shard file present, manifest missing
    torn = base / "ckpt-0000000009.d"
    torn.mkdir(parents=True)
    save_pytree(str(torn / "shard-00000.bin"), {"proc": 0, "chunks": {}})
    assert ck.steps() == [3]
    step, _ = ck.restore()
    assert step == 3


def test_process_local_arrays_dedupe_proc0_wins(tmp_path):
    """A fully-addressable (process-local) jax array makes EVERY process
    emit a full-range chunk — exact-duplicate ranges must restore with
    process 0's copy winning (legacy proc-0-writes discipline), counted
    once in the coverage check."""
    import jax

    from dmlc_core_tpu.checkpoint import (
        load_pytree_sharded,
        save_pytree_sharded,
    )

    base = str(tmp_path / "ck.d")
    # simulate 2 processes saving: each holds a DIFFERENT local copy
    for pid, fill in ((0, 1.0), (1, 2.0)):
        local = jax.device_put(np.full(4, fill, np.float32))
        assert local.is_fully_addressable
        save_pytree_sharded(base, {"step_ctr": local}, pid, 2)
    back = load_pytree_sharded(base)
    np.testing.assert_array_equal(back["step_ctr"], np.full(4, 1.0))


def test_prune_removes_torn_debris(tmp_path):
    from dmlc_core_tpu.checkpoint import Checkpointer, save_pytree

    base = tmp_path / "ck"
    ck = Checkpointer(str(base), keep=2, process_index=0)
    ck.save(1, {"w": np.ones(2, np.float32)})
    # crash debris: torn .d (no manifest) + orphaned .tmp, both older
    # than the next complete save
    torn = base / "ckpt-0000000002.d"
    torn.mkdir()
    save_pytree(str(torn / "shard-00000.bin"), {"proc": 0, "chunks": {}})
    (base / "ckpt-0000000002.bin.tmp").write_bytes(b"junk")
    ck.save(3, {"w": np.ones(2, np.float32)})
    names = set(os.listdir(base))
    assert "ckpt-0000000002.d" not in names
    assert "ckpt-0000000002.bin.tmp" not in names
    assert ck.steps() == [1, 3]


def test_same_step_resave_never_shadowed(tmp_path):
    """Re-saving a step in the OTHER layout must invalidate the old one:
    a stale .d may not shadow a newer .bin and vice versa."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.parallel import make_mesh

    mesh = make_mesh((8,), ("data",))
    ck = Checkpointer(str(tmp_path / "ck"), process_index=0)
    old = jax.device_put(
        np.zeros(8, np.float32), NamedSharding(mesh, P("data"))
    )
    ck_sharded = Checkpointer(str(tmp_path / "ck"), sharded=True)
    ck_sharded.save(5, {"w": old})
    # legacy re-save of the SAME step with new data
    ck.save(5, {"w": np.ones(8, np.float32)})
    _, back = ck.restore()
    np.testing.assert_array_equal(np.asarray(back["w"]), np.ones(8))
    assert not os.path.isdir(tmp_path / "ck" / "ckpt-0000000005.d")
    # and the reverse: sharded re-save invalidates the .bin
    ck_sharded.save(
        5, {"w": jax.device_put(np.full(8, 2.0, np.float32),
                                NamedSharding(mesh, P("data")))}
    )
    _, back = ck_sharded.restore()
    np.testing.assert_array_equal(np.asarray(back["w"]), np.full(8, 2.0))
    assert not os.path.exists(tmp_path / "ck" / "ckpt-0000000005.bin")


def test_remote_same_step_resave_and_retention():
    """On an object-store backend (mem:// stands in) the same-step
    shadow fix and retention must work through FileSystem.delete — not
    silently no-op like the old local-only removal."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.io.filesystem import MemoryFileSystem
    from dmlc_core_tpu.parallel import make_mesh

    MemoryFileSystem.reset()
    try:
        mesh = make_mesh((8,), ("data",))
        base = "mem://ck/run1"
        sharded = Checkpointer(base, keep=2, sharded=True)
        legacy = Checkpointer(base, keep=2, process_index=0)
        old = jax.device_put(
            np.zeros(8, np.float32), NamedSharding(mesh, P("data"))
        )
        sharded.save(5, {"w": old})
        legacy.save(5, {"w": np.ones(8, np.float32)})  # same-step re-save
        _, back = legacy.restore()
        np.testing.assert_array_equal(np.asarray(back["w"]), np.ones(8))
        # retention across layouts on the remote store
        legacy.save(6, {"w": np.ones(8, np.float32)})
        sharded.save(
            7, {"w": jax.device_put(np.ones(8, np.float32),
                                    NamedSharding(mesh, P("data")))}
        )
        sharded.save(
            8, {"w": jax.device_put(np.ones(8, np.float32),
                                    NamedSharding(mesh, P("data")))}
        )
        assert sharded.steps() == [7, 8]  # 5 and 6 pruned remotely
    finally:
        MemoryFileSystem.reset()


def test_legacy_restore_applies_template(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.parallel import make_mesh

    ck = Checkpointer(str(tmp_path / "ck"), process_index=0)
    w = np.arange(16, dtype=np.float32)
    ck.save(2, {"w": w})
    mesh = make_mesh((8,), ("data",))
    tmpl = {"w": jax.device_put(w, NamedSharding(mesh, P("data")))}
    _, back = ck.restore(template=tmpl)
    assert back["w"].sharding == tmpl["w"].sharding
    np.testing.assert_array_equal(np.asarray(back["w"]), w)


def test_async_save_overlaps_and_restores(tmp_path):
    """save_async snapshots on the caller thread (donation-safe: device
    buffers may be deleted right after it returns) and writes in the
    background; restore/wait drain it."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.parallel import make_mesh

    mesh = make_mesh((4, 2), ("data", "model"))
    w = jax.device_put(
        np.arange(64, dtype=np.float32).reshape(8, 8),
        NamedSharding(mesh, P(None, "model")),
    )
    ck = Checkpointer(str(tmp_path / "ck"), sharded=True)
    handle = ck.save_async(4, {"w": w})
    # simulate donation: the device buffers die right after save_async
    w.delete()
    uri = handle.result(timeout=30)
    assert uri is not None and uri.endswith(".d") and handle.done()
    step, back = ck.restore()
    assert step == 4
    np.testing.assert_array_equal(
        back["w"], np.arange(64, dtype=np.float32).reshape(8, 8)
    )
    # consecutive async saves serialize and retention still applies
    for s in (5, 6, 7):
        ck.save_async(
            s, {"w": jax.device_put(np.full((8, 8), s, np.float32),
                                    NamedSharding(mesh, P(None, "model")))}
        )
    ck.wait()
    assert ck.steps() == [5, 6, 7]  # keep=3 pruned step 4


def test_async_save_snapshots_host_leaves(tmp_path):
    """In-place mutation of numpy leaves right after save_async returns
    must not leak into the background write (the snapshot owns its
    buffers — torn-checkpoint hazard otherwise)."""
    from dmlc_core_tpu.checkpoint import Checkpointer

    counter = np.zeros(4, np.float32)
    ck = Checkpointer(str(tmp_path / "ck"), process_index=0, sharded=False)
    handle = ck.save_async(1, {"counter": counter})
    counter += 99.0  # "next step" mutates host state in place
    handle.result(timeout=30)
    _, back = ck.restore()
    np.testing.assert_array_equal(back["counter"], np.zeros(4))


def test_async_save_failure_surfaces(tmp_path):
    from dmlc_core_tpu.checkpoint import Checkpointer
    from dmlc_core_tpu.utils.logging import Error as DmlcError

    target = tmp_path / "blocked"
    target.write_text("a file where the checkpoint dir must go")
    ck = Checkpointer(str(target / "sub"), process_index=0, sharded=False)
    handle = ck.save_async(1, {"w": np.ones(3, np.float32)})
    with pytest.raises((OSError, DmlcError)):
        handle.result(timeout=30)


N_STEPS = 6
CKPT_STEP = 3

WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
import jax

jax.config.update("jax_platforms", "cpu")
if {nprocs} > 1:
    jax.distributed.initialize(
        coordinator_address={coord!r},
        num_processes={nprocs},
        process_id={pid},
    )
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlc_core_tpu.checkpoint import Checkpointer
from dmlc_core_tpu.models import FactorizationMachine
from dmlc_core_tpu.parallel import data_parallel_step, make_mesh

NUM_FEATURES, EMBED, BATCH, K = 64, 8, 16, 4
RULES = {{"v": P(None, "model")}}

mesh = make_mesh((4, 2), ("data", "model"))  # 8 global devices

def gput(x, spec):
    x = np.asarray(x)
    sh = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

model = FactorizationMachine(NUM_FEATURES, EMBED)
host_init = {{k: np.asarray(v) for k, v in
             model.init(jax.random.PRNGKey(0)).items()}}
params = {{k: gput(v, RULES.get(k, P())) for k, v in host_init.items()}}
if {nprocs} > 1:
    assert not params["v"].is_fully_addressable  # the r3 crash precondition

def checksums(tree):
    # deterministic per-param scalar on the global mesh (same partitioned
    # reduction across process counts) — comparable bit-for-bit between a
    # 2-proc save and a 1- or 4-proc restore
    out = []
    for k in sorted(tree):
        s = jax.jit(lambda x: (x.astype('float32') ** 2).sum())(tree[k])
        out.append(np.float32(s).tobytes().hex())
    return " ".join(out)

def batches():
    rng = np.random.default_rng(42)
    out = []
    for _ in range({n_steps}):
        out.append({{
            "indices": gput(rng.integers(0, NUM_FEATURES, (BATCH, K))
                            .astype(np.int32), P("data", None)),
            "values": gput(rng.normal(size=(BATCH, K)).astype(np.float32),
                           P("data", None)),
            "nnz": gput(np.full(BATCH, K, np.int32), P("data")),
            "labels": gput(rng.integers(0, 2, BATCH).astype(np.float32),
                           P("data")),
            "weights": gput(np.ones(BATCH, np.float32), P("data")),
        }})
    return out

step = data_parallel_step(
    lambda p, b: model.sgd_step(p, b, lr=0.1), mesh,
    param_rules=RULES, donate_params=False,
)
ck = Checkpointer({ckdir!r})
mode = {mode!r}
losses = []
sums = ""
bs = batches()
if mode == "straight":
    for i in range({n_steps}):
        params, loss = step(params, bs[i])
        losses.append(float(loss))
        if i + 1 == {ckpt_step}:
            sums = checksums(params)
            uri = ck.save(i + 1, params)
            assert uri is not None and uri.endswith(".d"), uri
elif mode == "straight_async":
    handle = None
    for i in range({n_steps}):
        params, loss = step(params, bs[i])
        losses.append(float(loss))
        if i + 1 == {ckpt_step}:
            # async write overlaps the REMAINING training steps; its
            # coordination-service barriers must not deadlock against
            # the training step's device collectives
            handle = ck.save_async(i + 1, params)
    uri = handle.result(timeout=120)
    assert uri is not None and uri.endswith(".d"), uri
else:
    got_step, params = ck.restore(template=params)
    assert got_step == {ckpt_step}, got_step
    if {nprocs} > 1:
        assert not params["v"].is_fully_addressable
    sums = checksums(params)
    for i in range({ckpt_step}, {n_steps}):
        params, loss = step(params, bs[i])
        losses.append(float(loss))

with open({out!r} + str({pid}), "w") as f:
    f.write(sums + "|")
    f.write(" ".join(np.float32(x).tobytes().hex() for x in losses))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_group(tmp_path, tag, mode, ckdir, out, nprocs=2, ndev=4):
    """Launch ``nprocs`` real processes with ``ndev`` virtual CPU devices
    each (global mesh stays 4x2 = 8 devices across every configuration)."""
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={ndev}"]
    )
    procs = []
    for pid in range(nprocs):
        script = tmp_path / f"{tag}{pid}.py"
        script.write_text(
            textwrap.dedent(
                WORKER.format(
                    repo=REPO, coord=coord, pid=pid, ckdir=ckdir,
                    mode=mode, out=out, n_steps=N_STEPS,
                    ckpt_step=CKPT_STEP, nprocs=nprocs,
                )
            )
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    try:
        outs = [p.communicate(timeout=300) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, (o, e) in zip(procs, outs):
        assert p.returncode == 0, f"{tag} worker failed:\n{o}\n{e}"


def _run_pair(tmp_path, tag, mode, ckdir, out):
    _run_group(tmp_path, tag, mode, ckdir, out, nprocs=2, ndev=4)


def _read_out(path):
    """(checksums, losses) from a worker's output file."""
    sums, losses = open(path).read().split("|")
    return sums, losses.split()


@pytest.mark.slow
@pytest.mark.parametrize("save_mode", ["straight", "straight_async"])
def test_two_process_midrun_checkpoint_resume_bitexact(tmp_path, save_mode):
    """Straight 6-step run (checkpointing at step 3) == restart from the
    step-3 checkpoint and run steps 4-6: loss trajectories bit-identical,
    with v tp-sharded P(None,'model') across 2 processes the whole time.
    The async variant keeps training DURING the background write."""
    ckdir = str(tmp_path / "ck")
    out_s = str(tmp_path / "straight")
    out_r = str(tmp_path / "resume")
    _run_pair(tmp_path, "s", save_mode, ckdir, out_s)

    # the sharded layout really is multi-file: one shard per process
    dirs = [d for d in os.listdir(ckdir) if d.endswith(".d")]
    assert len(dirs) == 1
    files = sorted(os.listdir(os.path.join(ckdir, dirs[0])))
    assert files == ["MANIFEST.bin", "shard-00000.bin", "shard-00001.bin"]

    _run_pair(tmp_path, "r", "resume", ckdir, out_r)

    for pid in range(2):
        _, straight = _read_out(out_s + str(pid))
        _, resumed = _read_out(out_r + str(pid))
        assert len(straight) == N_STEPS and len(resumed) == N_STEPS - CKPT_STEP
        # bit-for-bit: hex of the float32 payloads, not approx-equal
        assert straight[CKPT_STEP:] == resumed, (straight, resumed)


@pytest.fixture(scope="module")
def two_proc_checkpoint(tmp_path_factory):
    """One shared 2-process straight run + its step-3 checkpoint for
    every elastic-restore case (identical inputs — no reason to retrain
    per parametrization)."""
    base = tmp_path_factory.mktemp("elastic")
    ckdir = str(base / "ck")
    out_s = str(base / "straight")
    _run_group(base, "s", "straight", ckdir, out_s, nprocs=2, ndev=4)
    sums_saved, straight = _read_out(out_s + "0")
    return base, ckdir, sums_saved, straight


@pytest.mark.slow
@pytest.mark.parametrize(
    "nprocs,ndev", [(1, 8), (4, 2)], ids=["2to1", "2to4"]
)
def test_elastic_restore_across_process_counts(
    two_proc_checkpoint, nprocs, ndev
):
    """The elastic-recovery story the manifest/template design promises
    (checkpoint.py module docs): save at 2 processes, restore at 1 and
    at 4 — the global mesh stays 4x2, each restoring process reassembles
    the global tree from BOTH saved shard files and re-places it onto
    its own addressable slice. Param checksums (partitioned global
    reductions) must match the save-time values bit-for-bit. The resumed
    loss trajectory is compared to the uninterrupted 2-process run at
    1-ulp tolerance: restored STATE is exact, but a psum across a
    different process topology may legally reassociate the floating-
    point reduction (observed: one trailing-bit flip by step 5)."""
    base, ckdir, sums_saved, straight = two_proc_checkpoint
    out_r = str(base / f"resume{nprocs}")
    _run_group(
        base, f"e{nprocs}", "resume", ckdir, out_r,
        nprocs=nprocs, ndev=ndev,
    )
    def floats(hexes):
        return np.array(
            [np.frombuffer(bytes.fromhex(h), np.float32)[0] for h in hexes]
        )

    for pid in range(nprocs):
        sums_restored, resumed = _read_out(out_r + str(pid))
        assert sums_restored == sums_saved, (sums_restored, sums_saved)
        a, b = floats(straight[CKPT_STEP:]), floats(resumed)
        ulps = np.abs(
            a.view(np.int32).astype(np.int64)
            - b.view(np.int32).astype(np.int64)
        )
        assert ulps.max() <= 1, (straight, resumed, ulps)
