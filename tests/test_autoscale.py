"""Closed-loop elastic autoscaling (tracker/autoscale.py, ISSUE 16):
the pure control law on canned windowed series (hysteresis, dwell,
cost ceiling, flap budget, bounds), deterministic offline replay +
the ``tools autoscale replay`` CLI, the controller tick against fake
aggregator/actuator/clock, the aggregator's extra report sections,
the ``tools top`` autoscale surface, and the dmlc-submit drill — an
injected ``fault://latency_ms`` input-bound phase provokes a real
scale-up and the stall fraction shrinks once the fleet grows."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dmlc_core_tpu.telemetry import timeseries as ts
from dmlc_core_tpu.tracker import autoscale as asc


def _cfg(**kw):
    base = dict(
        min_workers=1, max_workers=4, up_threshold=0.40,
        down_threshold=0.10, dwell_secs=10.0, cost_ceiling=0.0,
        interval=2.0, window=10.0, max_flaps=4,
    )
    base.update(kw)
    return asc.AutoscaleConfig(**base)


def _view(input_stall=0.0, compute_stall=0.0, ranks=1, queue=0.0,
          samples=5):
    """A canned ``ClusterTimeSeries.window()`` shape: per-rank windows
    for ``ranks`` reporting workers + the tracker pseudo-rank carrying
    the shard queue-depth gauge, and the merged cluster stall view."""
    per_rank = {
        str(r): {"samples": samples, "span_secs": 4.0, "counters": {},
                 "gauges": {}, "histograms": {}, "derived": {}}
        for r in range(ranks)
    }
    per_rank["tracker"] = {
        "samples": samples, "span_secs": 4.0, "counters": {},
        "gauges": {"tracker.shards.queue_depth":
                   {"last": queue, "min": 0.0, "max": queue}},
        "histograms": {}, "derived": {},
    }
    stall = {}
    if input_stall:
        # split across two input stages: decide() must SUM the family
        stall["shard_lease_wait"] = input_stall / 2
        stall["dsserve_recv_wait"] = input_stall / 2
    if compute_stall:
        stall["dispatch_slot_wait"] = compute_stall
    return {
        "window_secs": 10.0,
        "per_rank": per_rank,
        "cluster": {"n_ranks": ranks,
                    "derived": {"stall_fraction": stall}},
    }


# -- signals ------------------------------------------------------------------


def test_signals_sums_stage_families_and_counts_ranks():
    sig = asc.signals(_view(input_stall=0.5, compute_stall=0.2,
                            ranks=3, queue=7.0))
    assert sig["input_stall"] == pytest.approx(0.5)
    assert sig["compute_stall"] == pytest.approx(0.2)
    assert sig["queue_depth"] == 7.0
    assert sig["reporting_ranks"] == 3


def test_signals_ignores_thin_windows_and_tracker_rank():
    # one sample is not a window; the tracker pseudo-rank never counts
    sig = asc.signals(_view(samples=1))
    assert sig["reporting_ranks"] == 0
    assert asc.signals({"per_rank": {}, "cluster": {}})[
        "reporting_ranks"
    ] == 0


# -- the pure control law -----------------------------------------------------


def test_scale_up_on_sustained_input_stall():
    st = asc.ControllerState(target=1)
    a = asc.decide(_view(input_stall=0.6), st, _cfg(), now=100.0)
    assert a.kind == asc.SCALE_UP and a.reason == "input_bound"
    assert a.target == 2
    asc.apply_action(st, a, 100.0)
    assert st.target == 2 and st.last_direction == 1


def test_hold_inside_hysteresis_band():
    st = asc.ControllerState(target=2)
    a = asc.decide(_view(input_stall=0.25), st, _cfg(), now=100.0)
    assert a.kind == asc.HOLD and a.reason == "in_band"
    assert a.target == 2  # a hold never moves the target


def test_no_signal_without_reporting_ranks():
    """An empty window (job just started, sampling off, every worker
    silent) must HOLD — never actuate blind."""
    st = asc.ControllerState(target=1)
    a = asc.decide(_view(input_stall=0.9, samples=1), st, _cfg(), 100.0)
    assert a.kind == asc.HOLD and a.reason == "no_signal"


def test_compute_bound_triggers_scale_down():
    st = asc.ControllerState(target=3)
    a = asc.decide(
        _view(input_stall=0.05, compute_stall=0.7), st, _cfg(), 100.0
    )
    assert a.kind == asc.SCALE_DOWN and a.reason == "compute_bound"
    assert a.target == 2


def test_bounds_at_min_and_at_max():
    cfg = _cfg(min_workers=1, max_workers=3)
    st = asc.ControllerState(target=3)
    assert asc.decide(_view(input_stall=0.9), st, cfg, 0.0).reason == (
        "at_max"
    )
    st = asc.ControllerState(target=1)
    assert asc.decide(_view(input_stall=0.0), st, cfg, 0.0).reason == (
        "at_min"
    )


def test_dwell_suppresses_flapping():
    """Within dwell_secs of the last action the controller holds even
    on a strong opposite signal; once the dwell expires it acts."""
    cfg = _cfg(dwell_secs=10.0)
    st = asc.ControllerState(target=1)
    asc.apply_action(
        st, asc.decide(_view(input_stall=0.8), st, cfg, 100.0), 100.0
    )
    assert st.target == 2
    # 4s later the signal reverses hard — dwell wins
    a = asc.decide(_view(input_stall=0.0), st, cfg, 104.0)
    assert a.kind == asc.HOLD and a.reason == "dwell"
    # past the dwell the reversal is honored
    a = asc.decide(_view(input_stall=0.0), st, cfg, 111.0)
    assert a.kind == asc.SCALE_DOWN


def test_cost_ceiling_stops_ups_but_not_downs():
    cfg = _cfg(cost_ceiling=100.0, dwell_secs=0.0)
    st = asc.ControllerState(target=2)
    st.cost_spent = 100.0  # budget gone
    a = asc.decide(_view(input_stall=0.9), st, cfg, 100.0)
    assert a.kind == asc.HOLD and a.reason == "cost_ceiling"
    # retiring still works — the ceiling caps SPEND, not shrink
    a = asc.decide(_view(input_stall=0.0), st, cfg, 100.0)
    assert a.kind == asc.SCALE_DOWN


def test_flap_budget_refuses_reversals_not_continuations():
    cfg = _cfg(dwell_secs=0.0, max_flaps=2)
    st = asc.ControllerState(target=2, last_direction=-1,
                             direction_changes=2)
    a = asc.decide(_view(input_stall=0.9), st, cfg, 100.0)
    assert a.kind == asc.HOLD and a.reason == "flap_budget"
    # continuing the CURRENT direction is always allowed
    a = asc.decide(_view(input_stall=0.0), st, cfg, 100.0)
    assert a.kind == asc.SCALE_DOWN


def test_apply_action_counts_direction_changes():
    st = asc.ControllerState(target=1)
    asc.apply_action(st, asc.Action(asc.SCALE_UP, "input_bound", 2), 1.0)
    asc.apply_action(st, asc.Action(asc.SCALE_UP, "input_bound", 3), 2.0)
    assert st.direction_changes == 0  # same direction is not a flap
    asc.apply_action(
        st, asc.Action(asc.SCALE_DOWN, "compute_bound", 2), 3.0
    )
    assert st.direction_changes == 1
    assert st.decisions == {"scale_up": 2, "scale_down": 1}


def test_accrue_cost_integrates_worker_seconds():
    st = asc.ControllerState(target=2)
    asc.accrue_cost(st, 2, 100.0)   # first tick only arms the clock
    assert st.cost_spent == 0.0
    asc.accrue_cost(st, 2, 110.0)
    assert st.cost_spent == pytest.approx(20.0)
    asc.accrue_cost(st, 3, 112.0)
    assert st.cost_spent == pytest.approx(26.0)


def test_config_validation():
    with pytest.raises(ValueError, match="bounds"):
        _cfg(min_workers=3, max_workers=2)
    with pytest.raises(ValueError, match="hysteresis"):
        _cfg(up_threshold=0.1, down_threshold=0.4)


def test_config_from_env(monkeypatch):
    monkeypatch.delenv("DMLC_AUTOSCALE", raising=False)
    assert asc.AutoscaleConfig.from_env() is None
    monkeypatch.setenv("DMLC_AUTOSCALE", "1:4")
    monkeypatch.setenv("DMLC_AUTOSCALE_DWELL", "3.5")
    monkeypatch.setenv("DMLC_AUTOSCALE_COST_CEILING", "120")
    cfg = asc.AutoscaleConfig.from_env()
    assert (cfg.min_workers, cfg.max_workers) == (1, 4)
    assert cfg.dwell_secs == 3.5 and cfg.cost_ceiling == 120.0
    monkeypatch.setenv("DMLC_AUTOSCALE", "banana")
    with pytest.raises(ValueError, match="min:max"):
        asc.AutoscaleConfig.from_env()


# -- offline replay ------------------------------------------------------------


def _recorded_report(phases, dt=1.0):
    """A canned end-of-job ``timeseries`` section: one worker rank whose
    ``trace.stall_seconds{stage="shard_lease_wait"}`` counter grows at
    the per-phase rate (the stall fraction the windowed view derives)."""
    samples, t, stall, seq = [], 1000.0, 0.0, 0
    for dur, rate in phases:
        for _ in range(int(dur / dt)):
            t += dt
            stall += rate * dt
            seq += 1
            samples.append({
                "t": t, "seq": seq,
                "counters": {
                    'trace.stall_seconds{stage="shard_lease_wait"}':
                        round(stall, 6),
                    "io.split.records": 100.0 * seq,
                },
                "gauges": {}, "histograms": {},
            })
    return {"per_rank": {"0": samples}}


def test_replay_scales_up_in_the_stall_phase_and_is_deterministic():
    report = _recorded_report([(10, 0.0), (12, 0.9)])
    cfg = _cfg(max_workers=3, interval=2.0, window=4.0, dwell_secs=4.0)
    first = asc.replay(report, cfg)
    assert first == asc.replay(report, cfg)  # byte-for-byte repeatable
    ups = [d for d in first if d["kind"] == asc.SCALE_UP]
    assert ups and all(d["t"] > 10.0 for d in ups)
    assert ups[0]["input_stall"] >= cfg.up_threshold
    # the calm phase never scales (at_min holds, nothing actuated)
    assert all(
        d["kind"] == asc.HOLD for d in first if d["t"] <= 10.0
    )
    # cost integrates the simulated fleet monotonically
    costs = [d["cost_spent"] for d in first]
    assert costs == sorted(costs)
    acts = asc.replay(report, cfg, include_holds=False)
    assert [d["kind"] for d in acts] == [asc.SCALE_UP] * len(ups)


def test_replay_empty_series_is_empty():
    assert asc.replay({"per_rank": {}}, _cfg()) == []


def test_tools_autoscale_replay_cli(tmp_path, capsys):
    from dmlc_core_tpu import tools

    report = {"timeseries": _recorded_report([(10, 0.0), (12, 0.9)])}
    path = tmp_path / "report.json"
    path.write_text(json.dumps(report))
    rc = tools.main([
        "autoscale", "replay", str(path), "--fleet", "1:3",
        "--interval", "2", "--window", "4", "--dwell", "4", "--json",
    ])
    assert rc == 0
    decisions = json.loads(capsys.readouterr().out)
    assert any(d["kind"] == "scale_up" for d in decisions)
    # the human rendering summarizes kinds + plan cost
    rc = tools.main([
        "autoscale", "replay", str(path), "--fleet", "1:3",
        "--interval", "2", "--window", "4", "--dwell", "4",
    ])
    out = capsys.readouterr().out
    assert rc == 0 and "scale_up" in out and "worker-seconds" in out
    # a report without a retained series is a loud error, not a crash
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({"cluster": {}}))
    assert tools.main(["autoscale", "replay", str(bare)]) == 1
    # malformed fleet bounds surface the config error
    assert tools.main([
        "autoscale", "replay", str(path), "--fleet", "3:1",
    ]) == 1


# -- the controller tick -------------------------------------------------------


class _FakeAgg:
    def __init__(self, view):
        self.view = view

    def windowed(self, seconds):
        return self.view


class _FakeActuator:
    def __init__(self, actual=1):
        self.n = actual
        self.adds = 0
        self.retires = 0

    def actual(self):
        return self.n

    def add_task(self):
        self.n += 1
        self.adds += 1
        return True

    def retire_task(self):
        self.n -= 1
        self.retires += 1
        return True


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_controller_tick_actuates_and_reports():
    agg = _FakeAgg(_view(input_stall=0.7))
    act = _FakeActuator(actual=1)
    clk = _Clock()
    ctl = asc.AutoscaleController(
        agg, _cfg(max_workers=3, dwell_secs=2.0), actuator=act, clock=clk
    )
    a = ctl.tick()
    assert a.kind == asc.SCALE_UP and act.adds == 1 and act.n == 2
    # dwell: the immediate next tick holds even though still stalled
    clk.t += 0.5
    assert ctl.tick().reason == "dwell" and act.adds == 1
    clk.t += 3.0
    assert ctl.tick().kind == asc.SCALE_UP and act.n == 3
    st = ctl.status()
    # "actual" is the fleet as READ at the last tick's start — the
    # third tick saw 2 workers and then actuated the third
    assert st["target"] == 3 and st["actual"] == 2
    assert st["decisions"]["scale_up"] == 2
    assert st["last"]["kind"] == "scale_up"
    assert st["cost_spent"] > 0
    # the phase flips compute-bound → graceful retire
    agg.view = _view(input_stall=0.02, compute_stall=0.6)
    clk.t += 3.0
    assert ctl.tick().kind == asc.SCALE_DOWN and act.retires == 1


def test_controller_first_tick_adopts_launched_fleet():
    """--dsserve N above min is the operator's opening bid: the first
    tick syncs target to the ACTUAL fleet instead of retiring it."""
    ctl = asc.AutoscaleController(
        _FakeAgg(_view(input_stall=0.25)),
        _cfg(min_workers=1, max_workers=4),
        actuator=_FakeActuator(actual=3),
        clock=_Clock(),
    )
    ctl.tick()
    assert ctl.status()["target"] == 3


def test_controller_shadow_mode_without_actuator():
    """No registered actuator (non-local backend): decisions are still
    recorded — nothing to actuate, nothing crashes."""
    asc.set_actuator(None)
    ctl = asc.AutoscaleController(
        _FakeAgg(_view(input_stall=0.9)), _cfg(dwell_secs=0.0),
        clock=_Clock(),
    )
    assert ctl.tick().kind == asc.SCALE_UP
    assert ctl.status()["target"] == 2


def test_actuator_registry_roundtrip():
    probe = _FakeActuator()
    asc.set_actuator(probe)
    try:
        assert asc.active_actuator() is probe
    finally:
        asc.set_actuator(None)
    assert asc.active_actuator() is None


# -- report plumbing -----------------------------------------------------------


def test_aggregator_extra_sections_in_report():
    from dmlc_core_tpu.telemetry.aggregate import ClusterAggregator

    agg = ClusterAggregator()
    agg.extra_sections["autoscale"] = lambda: {"target": 2}
    def boom():
        raise RuntimeError("status bug")
    agg.extra_sections["broken"] = boom
    rep = agg.report()
    assert rep["autoscale"] == {"target": 2}
    assert "broken" not in rep  # a failing section is dropped, not fatal
    assert "cluster" in rep  # and costs nothing else


def test_top_model_and_render_carry_autoscale():
    from dmlc_core_tpu.tools import _render_top, _top_model

    status = {
        "min_workers": 1, "max_workers": 4, "target": 3, "actual": 2,
        "cost_spent": 37.2, "cost_ceiling": 120.0,
        "direction_changes": 1,
        "decisions": {"hold": 9, "scale_up": 2},
        "last": {"kind": "scale_up", "reason": "input_bound"},
    }
    report = {
        "windowed": {"per_rank": {}, "cluster": {"n_ranks": 0,
                                                 "derived": {}}},
        "autoscale": status,
    }
    model = _top_model(report, 30.0)
    assert model["autoscale"] == status
    frame = _render_top(model, "http://t:1")
    assert "autoscale fleet 2→3 (bounds 1:4)" in frame
    assert "last scale_up (input_bound)" in frame
    assert "cost 37/120 ws" in frame
    assert "flaps 1" in frame
    # fixed-fleet jobs have no section and no line
    assert "autoscale" not in _render_top(
        _top_model({"windowed": report["windowed"]}, 30.0), "http://t:1"
    )


# -- THE dmlc-submit drill -----------------------------------------------------

_DRILL_WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
from dmlc_core_tpu.dsserve import DsServeBatches
from dmlc_core_tpu.staging.batcher import BatchSpec
from dmlc_core_tpu.tracker.client import RabitWorker

w = RabitWorker()
w.start()
spec = BatchSpec(batch_size=64, layout="ell", max_nnz=8)
last_hb = 0.0
for epoch in range({epochs}):
    src = DsServeBatches(
        "dsserve://" + os.environ["DMLC_DSSERVE"] + "/" + {uri!r}, spec,
        mode="lease", epoch=epoch,
    )
    rows = 0
    for b in src:
        rows += b.n_valid
        now = time.monotonic()
        if now - last_hb > 0.25:
            # heartbeats ship the ring's samples mid-drain — the
            # controller's only eyes on the trainer's stall profile
            w.heartbeat()
            last_hb = now
    src.close()
    print("epoch", epoch, "rows", rows, flush=True)
w.heartbeat()
w.shutdown()
"""


def test_submit_autoscale_drill_scales_up_and_stall_shrinks(tmp_path):
    """ISSUE 16 acceptance: ``dmlc-submit --autoscale 1:2`` over a
    corpus whose reads are fault://-latency-injected (every read slow —
    a sustained input-bound phase). The controller must observe the
    trainer's recv-wait stall, scale the dsserve tier up at least once,
    and the input-stall fraction must SHRINK after the fleet grows.
    Every epoch still drains exactly N_ROWS (elastic join mid-job is
    loss-free: endpoints-file discovery + the shard ledger)."""
    import numpy as np

    from dmlc_core_tpu.data.rowrec import encode_row
    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream

    n_rows, k = 2000, 8
    rec, idx = str(tmp_path / "d.rec"), str(tmp_path / "d.idx")
    with FileStream(rec, "w") as f, FileStream(idx, "w") as fi:
        wtr = IndexedRecordIOWriter(f, fi)
        rng = np.random.default_rng(7)
        for i in range(n_rows):
            wtr.write_record(encode_row(
                float(i % 2), rng.integers(0, 500, k, dtype=np.int64),
                rng.normal(size=k).astype(np.float32),
            ), i)
        wtr.flush_block()
    # every data read eats ~25ms: spikes must OUTNUMBER the reads per
    # open (the default is 2 — two blips, not a phase) and a small cap
    # multiplies the read count (io/faults.py schedule semantics)
    uri = (
        f"fault://latency_ms=25,spikes=400,cap=2048,seed=5{rec}"
        f"?index={idx}&shuffle=record&seed=3"
    )
    epochs = 4
    report_path = tmp_path / "report.json"
    script = tmp_path / "worker.py"
    script.write_text(
        _DRILL_WORKER.format(repo=REPO, uri=uri, epochs=epochs)
    )
    env = os.environ.copy()
    env.update({
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "DMLC_RENDEZVOUS_GRACE": "1",
        "DMLC_TS_INTERVAL": "0.1",
        "DMLC_AUTOSCALE_INTERVAL": "0.3",
        "DMLC_AUTOSCALE_WINDOW": "2",
        "DMLC_METRICS_REPORT": str(report_path),
    })
    for key in ("DMLC_TRACKER_URI", "DMLC_TRACKER_PORT",
                "DMLC_SHARD_RANK", "DMLC_DSSERVE", "DMLC_DSSERVE_FILE"):
        env.pop(key, None)
    proc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.tracker.submit",
         "--cluster", "local", "--num-workers", "1",
         "--autoscale", "1:2", "--autoscale-dwell", "0.5",
         "--shard-oversplit", "6",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    rows = [
        int(line.split()[-1])
        for line in proc.stdout.splitlines()
        if line.startswith("epoch")
    ]
    assert rows == [n_rows] * epochs, rows

    report = json.loads(report_path.read_text())
    status = report["autoscale"]
    assert status["decisions"].get("scale_up", 0) >= 1, status
    assert status["target"] == 2, status
    assert status["cost_spent"] > 0
    # the stall SHRANK once the second worker joined: window the
    # recorded series around its first vs its last thirds
    series = report["timeseries"]["per_rank"]["0"]
    assert len(series) >= 9, len(series)
    third = len(series) // 3
    t_early = series[third]["t"]
    t_late = series[-1]["t"]

    def input_stall(upto, width):
        win = ts.windowed(
            [s for s in series if s["t"] <= upto], width, now=upto
        )
        frac = win["derived"].get("stall_fraction", {})
        return sum(
            frac.get(stage, 0.0) for stage in asc.INPUT_STAGES
        )

    width = max(2.0, (t_late - series[0]["t"]) / 3.0)
    early = input_stall(t_early, width)
    late = input_stall(t_late, width)
    assert early > 0.3, (early, late)  # the phase really was input-bound
    assert late < early, (early, late)
