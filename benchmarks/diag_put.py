"""Isolate device_put behavior on this platform: distinct vs reused
buffers, dispatch-blocking vs async, and per-call latency."""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np


def put_series(bufs, n, depth=3):
    import jax

    inflight = []
    t0 = time.perf_counter()
    dispatch = 0.0
    for i in range(n):
        td = time.perf_counter()
        inflight.append(jax.device_put(bufs[i % len(bufs)]))  # noqa: L007 (raw link probe)
        dispatch += time.perf_counter() - td
        if len(inflight) >= depth:
            jax.block_until_ready(inflight.pop(0))
    for d in inflight:
        jax.block_until_ready(d)
    dt = time.perf_counter() - t0
    nb = bufs[0].nbytes * n
    return {
        "secs": round(dt, 4),
        "dispatch_secs": round(dispatch, 4),
        "mb_per_sec": round(nb / dt / 1e6, 1),
    }


def main():
    import jax

    jax.local_devices()
    rng = np.random.default_rng(3)
    NB = 8060928
    N = 13
    distinct = [rng.integers(0, 255, NB, dtype=np.uint8) for _ in range(N)]
    ring3 = distinct[:3]
    one = distinct[:1]
    out = {"platform": jax.local_devices()[0].platform}
    for r in range(3):
        out[f"distinct13_{r}"] = put_series(distinct, N)
        out[f"ring3_{r}"] = put_series(ring3, N)
        out[f"same1_{r}"] = put_series(one, N)
        # fresh buffers every call (realloc) — matches what a
        # copy-on-stage producer would do
        fresh = [
            rng.integers(0, 255, NB, dtype=np.uint8) for _ in range(N)
        ]
        out[f"fresh13_{r}"] = put_series(fresh, N)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
