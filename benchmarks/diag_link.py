"""Characterize the tunneled host->TPU link: sustained rate, burst
size, per-put latency series. 60 puts x 8MB = ~480MB over whatever time
it takes."""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np


def main():
    import jax

    jax.local_devices()
    rng = np.random.default_rng(5)
    NB = 8060928
    bufs = [rng.integers(0, 255, NB, dtype=np.uint8) for _ in range(8)]
    N = 60
    times = []
    t_all = time.perf_counter()
    for i in range(N):
        t0 = time.perf_counter()
        d = jax.device_put(bufs[i % len(bufs)])  # noqa: L007 (raw link probe)
        jax.block_until_ready(d)
        times.append(round(time.perf_counter() - t0, 4))
    dt = time.perf_counter() - t_all
    mb = NB / 1e6
    print(json.dumps({
        "total_secs": round(dt, 2),
        "sustained_mb_per_sec": round(NB * N / dt / 1e6, 1),
        "per_put_mb_per_sec": [round(mb / t, 1) for t in times],
        "per_put_secs": times,
    }, indent=1))


if __name__ == "__main__":
    main()
