"""Diagnostic: attribute the rowrec→HBM infeed gap (VERDICT r4 weak #1).

Runs the rec f16 staged epoch with per-stage timing, then isolates each
suspect cost on the same host/device:

  A. staged epoch w/ stage breakdown (host_pull / stage_dispatch / wait)
  B. device_put-only of the packed buffers (no jit unpack)
  C. device_put + jit unpack (the production stage_batch path)
  D. raw probe (prestaged random buffers, same shape/depth)
  E. host-only parse epoch (fused producer, no device)

Prints one JSON blob. Not part of the bench contract; a scalpel.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


import bench  # reuse data generators + stream makers


def staged_epoch():
    import jax

    from dmlc_core_tpu.staging import StagingPipeline, drain_close

    stream, key, _ = bench._make_rec_stream("float16")
    t0 = time.perf_counter()
    pipe = StagingPipeline(stream, depth=3)
    last = None
    n = 0
    for dev in pipe:
        last = dev
        n += 1
    if last is not None:
        jax.block_until_ready(last[key])
    dt = time.perf_counter() - t0
    out = {
        "secs": dt,
        "rows_per_sec": pipe.rows_staged / dt,
        "batches": n,
        **{k: round(v, 4) for k, v in pipe.stage_seconds.items()},
    }
    drain_close(pipe, stream)
    return out


def packed_sizes():
    stream, _key, _ = bench._make_rec_stream("float16")
    sizes = []
    for b in stream:
        sizes.append(b.packed.nbytes if b.packed is not None else -1)
        if len(sizes) >= 2:
            break
    stream.close()
    return sizes


def put_only_epoch(unpack: bool):
    """Parse on host into ring slots, device_put each packed buffer
    (optionally + jit unpack) with depth-3 in-flight, block in order.
    Isolates transfer+dispatch from the pipeline's thread plumbing."""
    import jax

    from dmlc_core_tpu.staging.pipeline import (
        _packed_layout,
        _safe_host,
        _unpacker,
    )

    stream, _key, _ = bench._make_rec_stream("float16")
    dev = jax.local_devices()[0]
    t0 = time.perf_counter()
    inflight = []
    n = 0
    rows = 0
    for b in stream:
        if b.packed is None:
            raise RuntimeError("no packed buffer")
        u8 = jax.device_put(_safe_host(b.packed, dev.platform), dev)
        if unpack:
            layout = _packed_layout(b)
            u8 = _unpacker(layout, dev.platform)(u8)
        inflight.append(u8)
        n += 1
        rows += b.n_valid
        if len(inflight) >= 3:
            jax.block_until_ready(inflight.pop(0))
    for x in inflight:
        jax.block_until_ready(x)
    dt = time.perf_counter() - t0
    stream.close()
    return {"secs": dt, "rows_per_sec": rows / dt, "batches": n}


def main():
    bench.ensure_native()
    bench.ensure_rec_data()
    import jax

    jax.local_devices()  # warm the backend outside any timer
    out = {}
    out["packed_nbytes"] = packed_sizes()
    # interleave two rounds so throttle hits everything equally
    for r in range(2):
        out[f"A_staged_{r}"] = staged_epoch()
        out[f"B_put_only_{r}"] = put_only_epoch(unpack=False)
        out[f"C_put_unpack_{r}"] = put_only_epoch(unpack=True)
        out[f"E_host_only_{r}"] = bench.host_epoch(bench._make_rec_stream)
        nb = out["packed_nbytes"][0]
        nbatches = out[f"A_staged_{r}"]["batches"]
        out[f"D_raw_{r}"] = bench.raw_infeed_probe(nb, nbatches)
    print(json.dumps(out, indent=1, default=float))


if __name__ == "__main__":
    main()
