"""Diagnostic: attribute the rowrec→HBM infeed gap (VERDICT r4 weak #1).

Runs the rec f16 staged epoch with per-stage timing, then isolates each
suspect cost on the same host/device:

  A. staged epoch w/ stage breakdown (host_pull / dispatch_pack /
     dispatch_put / slot_wait / transfer_wait)
  B. device_put-only of the packed buffers (no jit unpack)
  C. device_put + jit unpack, serial (the pre-ring stage_batch path)
  D. raw probe (prestaged random buffers, same shape/depth)
  E. host-only parse epoch (fused producer, no device)
  F. pack + ring-parallel put/unpack (the production dispatch ring
     isolated; F vs C is the dispatch-parallel win)

Prints one JSON blob. Not part of the bench contract; a scalpel.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


import bench  # reuse data generators + stream makers


def staged_epoch():
    import jax

    from dmlc_core_tpu.staging import StagingPipeline, drain_close

    stream, key, _ = bench._make_rec_stream("float16")
    t0 = time.perf_counter()
    pipe = StagingPipeline(stream, depth=3)
    last = None
    n = 0
    for dev in pipe:
        last = dev
        n += 1
    if last is not None:
        jax.block_until_ready(last[key])
    dt = time.perf_counter() - t0
    out = {
        "secs": dt,
        "rows_per_sec": pipe.rows_staged / dt,
        "batches": n,
        **{k: round(v, 4) for k, v in pipe.stage_seconds.items()},
    }
    drain_close(pipe, stream)
    return out


def packed_sizes():
    stream, _key, _ = bench._make_rec_stream("float16")
    sizes = []
    for b in stream:
        sizes.append(b.packed.nbytes if b.packed is not None else -1)
        if len(sizes) >= 2:
            break
    stream.close()
    return sizes


def put_only_epoch(unpack: bool):
    """Parse on host into ring slots, device_put each packed buffer
    (optionally + jit unpack) with depth-3 in-flight, block in order.
    Isolates transfer+dispatch from the pipeline's thread plumbing."""
    import jax

    from dmlc_core_tpu.staging.pipeline import (
        _packed_layout,
        _safe_host,
        _unpacker,
    )

    stream, _key, _ = bench._make_rec_stream("float16")
    dev = jax.local_devices()[0]
    t0 = time.perf_counter()
    inflight = []
    n = 0
    rows = 0
    for b in stream:
        if b.packed is None:
            raise RuntimeError("no packed buffer")
        u8 = jax.device_put(_safe_host(b.packed, dev.platform), dev)  # noqa: L007 (raw link probe)
        if unpack:
            layout = _packed_layout(b)
            u8 = _unpacker(layout, dev.platform)(u8)
        inflight.append(u8)
        n += 1
        rows += b.n_valid
        if len(inflight) >= 3:
            jax.block_until_ready(inflight.pop(0))
    for x in inflight:
        jax.block_until_ready(x)
    dt = time.perf_counter() - t0
    stream.close()
    return {"secs": dt, "rows_per_sec": rows / dt, "batches": n}


def ring_put_epoch(workers: int = 3):
    """The dispatch ring isolated: pack each packed batch into a stable
    fresh copy on THIS thread, dispatch the put+unpack on ``workers``
    pool threads (production ``_put_packed``), resolve in order. The
    delta vs C (serial put+unpack) is the dispatch-parallel win — on
    frontends where device_put blocks for the transfer's duration, C is
    serial-transfer-bound and this overlaps ``workers`` transfers.

    The pack copy is UNCONDITIONAL (np.array, fresh each batch), unlike
    ``_pack_single(…, slot=None)`` which skips the copy off-CPU: the
    production ring always pays one host memcpy per batch (into its
    reusable slot), and the async puts here must never read live
    producer ring slots — so this stage pays the same memcpy and stays
    aliasing-safe at any ``workers``."""
    import concurrent.futures as cf

    import jax

    from dmlc_core_tpu.staging.pipeline import (
        _packed_layout,
        _put_packed,
    )

    import numpy as np

    stream, _key, _ = bench._make_rec_stream("float16")
    dev = jax.local_devices()[0]
    pool = cf.ThreadPoolExecutor(max_workers=workers)
    t0 = time.perf_counter()
    inflight = []
    n = 0
    rows = 0
    pack_s = 0.0
    for b in stream:
        if b.packed is None:
            raise RuntimeError("no packed buffer")
        layout = _packed_layout(b)
        tp = time.perf_counter()
        src = np.array(b.packed, copy=True)
        pack_s += time.perf_counter() - tp
        inflight.append(pool.submit(_put_packed, src, layout, dev, None))
        n += 1
        rows += b.n_valid
        if len(inflight) >= workers:
            jax.block_until_ready(inflight.pop(0).result())
    for f in inflight:
        jax.block_until_ready(f.result())
    dt = time.perf_counter() - t0
    pool.shutdown()
    stream.close()
    return {
        "secs": dt,
        "rows_per_sec": rows / dt,
        "batches": n,
        "pack_secs": round(pack_s, 4),
    }


def main():
    trace_path = None
    if "--trace" in sys.argv:  # dump the flight recorder on exit
        i = sys.argv.index("--trace") + 1
        # bare --trace (path forgotten) falls back to the default path
        trace_path = (
            sys.argv[i]
            if i < len(sys.argv) and not sys.argv[i].startswith("--")
            else ""
        )
    bench.ensure_native()
    bench.ensure_rec_data()
    # 1 s registry sampling for the whole run: the exit summary prints
    # last-30s windowed rows/s + stall fractions next to the cumulative
    # A-F sums (a tail stall is invisible in whole-run averages)
    from dmlc_core_tpu.telemetry import timeseries as _timeseries

    ts_ring = _timeseries.TimeSeriesRing(interval=1.0)
    ts_ring.start()
    import jax

    jax.local_devices()  # warm the backend outside any timer
    out = {}
    out["packed_nbytes"] = packed_sizes()
    # interleave two rounds so throttle hits everything equally
    for r in range(2):
        out[f"A_staged_{r}"] = staged_epoch()
        out[f"B_put_only_{r}"] = put_only_epoch(unpack=False)
        out[f"C_put_unpack_{r}"] = put_only_epoch(unpack=True)
        out[f"F_ring_put_{r}"] = ring_put_epoch()
        out[f"E_host_only_{r}"] = bench.host_epoch(bench._make_rec_stream)
        nb = out["packed_nbytes"][0]
        nbatches = out[f"A_staged_{r}"]["batches"]
        out[f"D_raw_{r}"] = bench.raw_infeed_probe(nb, nbatches)
    print(json.dumps(out, indent=1, default=float))
    ts_ring.sample()  # reach "now" before the windowed query
    print(_timeseries.summary_line(ts_ring.window(30.0)))
    # exit dump of the telemetry registry: the same epochs as stage
    # duration HISTOGRAMS (p50/p90/p99 per stage) next to the A-F sums
    from dmlc_core_tpu.telemetry import to_json as telemetry_snapshot

    print("telemetry: " + json.dumps(telemetry_snapshot(), default=float))
    if trace_path is not None:
        from dmlc_core_tpu.telemetry import tracing

        path = tracing.dump(trace_path or None)
        print(
            f"trace: {path} — the A-F epochs above as a Perfetto "
            "timeline (https://ui.perfetto.dev; stall attribution: "
            f"python -m dmlc_core_tpu.tools trace report {path})"
        )


if __name__ == "__main__":
    main()
