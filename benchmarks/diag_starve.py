"""Does host CPU work between puts starve the tunnel IO threads on a
1-vCPU host? Compare put loops with: nothing / sleep(5ms) / GIL-holding
Python spin / GIL-releasing numpy copy between puts.

Verdict from the 2026-07-30 runs: no stable correlation — the rate
swings are dominated by the tunnel's token-bucket state, not by what
the host does between puts (see diag_link.py and bench.LinkProbe).

Second question (``--shuffle``): when the STAGED shuffled config
starves, is it the read layer? Drain the raw IndexedRecordIOSplitter
(no parse, no device) in each shuffle mode over the bench shard and
report rows/s plus the split's seek/span counters — the per-record
mode's seek storm vs the window mode's coalesced spans is visible here
without any device noise.

Third question (``--dynamic-shards``): what does tracker-leased
sharding cost when there is nothing to steal? Start a local tracker
in-process, drain the whole bench shard through DynamicShardSource
(every micro-shard leased by this one worker) and print the lease /
steal summary from both sides — the worker's lease_wait and the
ledger's granted/reclaimed/stolen — so the protocol overhead and the
straggler signal are observable outside bench's 3-process config."""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np



def spin(secs):
    t0 = time.perf_counter()
    x = 0
    while time.perf_counter() - t0 < secs:
        x += 1
    return x


def numpy_work(arr):
    # large memcpy-ish op; numpy releases the GIL for big copies
    return arr.copy()


def put_loop(bufs, n, between=None):
    import jax

    t0 = time.perf_counter()
    put_secs = 0.0
    for i in range(n):
        tp = time.perf_counter()
        d = jax.device_put(bufs[i % len(bufs)])  # noqa: L007 (raw link probe)
        jax.block_until_ready(d)
        put_secs += time.perf_counter() - tp
        if between is not None:
            between()
    dt = time.perf_counter() - t0
    return {
        "total_secs": round(dt, 3),
        "put_secs": round(put_secs, 3),
        "put_mb_per_sec": round(bufs[0].nbytes * n / put_secs / 1e6, 1),
    }


def shuffle_read_modes(fault: str = ""):
    """Raw split-layer drain per shuffle mode over the bench shard:
    rows/s + io_stats, no parse/device in the loop. Windowed modes
    (record/batch/window) drain through ``next_gather_batch`` — the
    zero-copy emission the fused staging layer consumes — so the
    gather_batches/gather_bytes counters and the gather-vs-legacy
    split-layer gap are visible here without any parse/device noise;
    ``legacy_record`` keeps the reference's per-record seek storm for
    contrast. ``fault`` is a fault:// spec (e.g.
    ``resets=2,errors=1,seed=7``): the drain then exercises the retry
    layer healing seeded faults, visible as
    retries/backoff_secs/faults_injected in the per-mode io_stats."""
    import bench
    from dmlc_core_tpu.io import split as io_split
    from dmlc_core_tpu.io.faults import wrap_uri

    bench.ensure_rec_data()
    bench.ensure_rec_index()
    out = {}
    for label, mode, extra in (
        ("0", "0", ""),
        ("record", "record", ""),
        ("legacy_record", "record", "&legacy_shuffle=1"),
        ("batch", "batch", "&batch_size=4096"),
        (
            "window",
            "window",
            f"&window={bench.WINDOW}&merge_gap={bench.MERGE_GAP}",
        ),
    ):
        uri = (
            f"{wrap_uri(bench.REC_DATA, fault)}?index={bench.REC_INDEX}"
            f"&shuffle={mode}{extra}"
        )
        s = io_split.create(uri, type="recordio", threaded=False)
        gather = getattr(s, "supports_gather", lambda: False)()
        t0 = time.perf_counter()
        nbytes = 0
        while True:
            if gather:
                g = s.next_gather_batch(4096)
                if g is None:
                    break
                nbytes += int(g[2].sum())
            else:
                chunk = s.next_batch(4096)
                if chunk is None:
                    break
                nbytes += len(chunk)
        dt = time.perf_counter() - t0
        stats = getattr(s, "io_stats", lambda: None)() or {}
        s.close()
        out[f"shuffle_{label}"] = {
            "rows_per_sec": round(stats.get("records", 0) / dt, 1),
            "mb_per_sec": round(nbytes / dt / 1e6, 1),
            "secs": round(dt, 3),
            "gather_drain": gather,
            **stats,
        }
    return out


def dynamic_shard_drain(fault: str = ""):
    """``--dynamic-shards``: drain the bench shard through
    DynamicShardSource against a local in-process tracker (ISSUE 10).
    One worker, so every micro-shard is self-leased — the number this
    isolates is the lease protocol's overhead (round-trips, lease_wait)
    on top of the identical windowed read path, with the ledger's
    grant/reclaim/steal shape printed on exit. ``fault`` wraps the DATA
    reads in a fault:// spec, making the TTL/renew machinery visible
    (latency spikes stretch shard drains toward the lease TTL)."""
    import bench
    from dmlc_core_tpu.io import split as io_split
    from dmlc_core_tpu.io.faults import wrap_uri
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    bench.ensure_rec_data()
    bench.ensure_rec_index()
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    prev_env = {
        k: os.environ.get(k)
        for k in ("DMLC_TRACKER_URI", "DMLC_TRACKER_PORT")
    }
    os.environ["DMLC_TRACKER_URI"] = "127.0.0.1"
    os.environ["DMLC_TRACKER_PORT"] = str(tracker.port)
    try:
        uri = (
            f"{wrap_uri(bench.REC_DATA, fault)}?index={bench.REC_INDEX}"
            "&shuffle=record&dynamic_shards=1"
        )
        s = io_split.create(uri, type="recordio", threaded=False)
        t0 = time.perf_counter()
        nbytes = 0
        while True:
            g = s.next_gather_batch(4096)
            if g is None:
                break
            nbytes += int(g[2].sum())
        dt = time.perf_counter() - t0
        stats = s.io_stats()
        s.close()
        return {
            "drain": {
                "rows_per_sec": round(stats.get("records", 0) / dt, 1),
                "mb_per_sec": round(nbytes / dt / 1e6, 1),
                "secs": round(dt, 3),
                **stats,
            },
            # the ledger's view: granted == completed and stolen == 0
            # on a healthy single-worker drain; reclaimed > 0 here
            # means shard drains outlived the lease TTL (renewal rides
            # the pulls, so that takes a genuine stall)
            "ledger": tracker.shards.summary(),
        }
    finally:
        tracker.close()
        # don't leak the dead tracker's address into the process
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def dsserve_drain(fault: str = ""):
    """``--dsserve``: drain the bench shard through the disaggregated
    preprocessing service end to end IN PROCESS (ISSUE 12): a local
    tracker, one DsServeServer thread leasing its micro-shards, and the
    ``dsserve://`` client source pulling packed slots over loopback.
    The numbers this isolates: the wire/framing overhead on top of the
    identical local pipeline (compare rows/s with ``--shuffle``'s
    window mode), the client's recv-wait profile (``dsserve_recv_wait``
    is where a network/server-bound trainer stalls), and the server's
    produce-vs-send overlap (queue_depth). ``fault`` wraps the DATA
    reads in a fault:// spec — the SERVER then rides the retry layer,
    the client only ever sees clean slots (chaos composes)."""
    import bench
    from dmlc_core_tpu.dsserve import DsServeBatches, DsServeServer
    from dmlc_core_tpu.io.faults import wrap_uri
    from dmlc_core_tpu.staging.batcher import BatchSpec
    from dmlc_core_tpu.telemetry import default_registry
    from dmlc_core_tpu.tracker.tracker import RabitTracker

    bench.ensure_rec_data()
    bench.ensure_rec_index()
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    prev_env = {
        k: os.environ.get(k)
        for k in ("DMLC_TRACKER_URI", "DMLC_TRACKER_PORT")
    }
    os.environ["DMLC_TRACKER_URI"] = "127.0.0.1"
    os.environ["DMLC_TRACKER_PORT"] = str(tracker.port)
    server = DsServeServer(rank=1001).start()
    try:
        uri = (
            f"{wrap_uri(bench.REC_DATA, fault)}?index={bench.REC_INDEX}"
            "&shuffle=record&seed=1"
        )
        spec = BatchSpec(
            batch_size=4096, layout="ell", max_nnz=bench.REC_K
        )
        src = DsServeBatches(
            f"dsserve://127.0.0.1:{server.port}"
            + ("" if uri.startswith("/") else "/") + uri,
            spec, mode="lease",
        )
        t0 = time.perf_counter()
        rows = nbytes = slots = 0
        for b in src:
            rows += b.n_valid
            nbytes += b.packed.nbytes
            slots += 1
        dt = time.perf_counter() - t0
        stats = src.io_stats()
        src.close()
        reg = default_registry()
        wait = reg.histogram("dsserve.recv_wait_seconds").snapshot()
        return {
            "drain": {
                "rows_per_sec": round(rows / dt, 1),
                "slot_mb_per_sec": round(nbytes / dt / 1e6, 1),
                "secs": round(dt, 3),
                "rows": rows,
                "slots": slots,
                **stats,
            },
            # per-stage view: recv_wait is the trainer-side stall (the
            # dsserve_recv_wait stage on a merged timeline); the
            # server's counters show what the preprocessing side did
            "recv_wait_seconds": {
                k: wait[k]
                for k in ("count", "p50", "p90", "p99")
                if k in wait
            },
            "server": server.stats(),
            "ledger": tracker.shards.summary(),
        }
    finally:
        server.close()
        tracker.close()
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


_TS_RING = None


def _start_timeseries() -> None:
    """Sample the registry every second for the run so the exit summary
    can print WINDOWED rates (last-30s rows/s + stall fractions) next
    to the cumulative totals — a long diag drain's tail behavior is
    otherwise averaged away by the whole-run numbers."""
    global _TS_RING
    from dmlc_core_tpu.telemetry import timeseries

    _TS_RING = timeseries.TimeSeriesRing(interval=1.0)
    _TS_RING.start()


def _print_windowed() -> None:
    if _TS_RING is None:
        return
    from dmlc_core_tpu.telemetry import timeseries

    _TS_RING.sample()  # reach "now" before querying
    print(timeseries.summary_line(_TS_RING.window(30.0)))


def _print_telemetry() -> None:
    """Exit dump of the process telemetry registry: every counter the
    drained layers ticked (split shape, retry/fault, staging) in one
    place — starvation diagnosis no longer means grepping the scattered
    per-mode io_stats dicts above it; the windowed line on top of it
    answers 'what was it doing at the END' (docs/observability.md)."""
    from dmlc_core_tpu.telemetry import to_json

    _print_windowed()
    print("telemetry: " + json.dumps(to_json()))


def _fetch_threads_arg() -> None:
    """``--fetch-threads N``: pin the span-fetch concurrency for the
    drains (exported as DMLC_FETCH_THREADS before any splitter is
    built; 1 = the serial baseline). Grow it across runs and watch the
    summary below — the observable version of what the AIMD ramp picks
    on its own."""
    if "--fetch-threads" not in sys.argv:
        return
    n = sys.argv[sys.argv.index("--fetch-threads") + 1]
    os.environ["DMLC_FETCH_THREADS"] = str(int(n))


def _print_fetch_summary() -> None:
    """Exit summary of the concurrent span fetcher (ISSUE 9): the peak
    concurrency the AIMD ramp actually reached plus the consumer-side
    span_wait_seconds percentiles and stream reopens — the autotune's
    chosen concurrency, observable outside bench. All zeros when every
    drain was local (the mmap fast path never engages the fetcher)."""
    from dmlc_core_tpu.io.spanfetch import fetch_threads
    from dmlc_core_tpu.telemetry import default_registry

    reg = default_registry()
    wait = reg.histogram("io.fetch.span_wait_seconds").snapshot()
    print(
        "fetch: "
        + json.dumps(
            {
                "fetch_threads": fetch_threads(),
                "concurrency_peak": reg.gauge(
                    "io.fetch.concurrency_peak"
                ).value(),
                "spans": reg.counter("io.fetch.spans").value(),
                "mb_fetched": round(
                    reg.counter("io.fetch.bytes").value() / 1e6, 2
                ),
                "reopens": reg.counter("io.fetch.reopens").value(),
                "span_wait_seconds": {
                    k: wait[k]
                    for k in ("count", "p50", "p90", "p99")
                    if k in wait
                },
            }
        )
    )


def _trace_arg():
    """``--trace <path>``: dump the flight recorder on exit (ISSUE 8)
    so the per-mode numbers above come WITH their timeline. A bare
    ``--trace`` (path forgotten, or followed by another flag) dumps to
    the recorder's default path instead of crashing."""
    if "--trace" not in sys.argv:
        return None
    i = sys.argv.index("--trace") + 1
    if i < len(sys.argv) and not sys.argv[i].startswith("--"):
        return sys.argv[i]
    return ""  # default path (tracing.default_trace_path)


def _dump_trace(path) -> None:
    if path is None:
        return
    from dmlc_core_tpu.telemetry import tracing

    out = tracing.dump(path or None)
    print(
        f"trace: {out} — the drains above as a Perfetto timeline "
        "(https://ui.perfetto.dev; stall attribution: "
        f"python -m dmlc_core_tpu.tools trace report {out})"
    )


def main():
    trace_path = _trace_arg()
    _fetch_threads_arg()
    _start_timeseries()
    if "--shuffle" in sys.argv:
        fault = ""
        if "--fault" in sys.argv:  # e.g. --fault resets=2,errors=1,seed=7
            fault = sys.argv[sys.argv.index("--fault") + 1]
        print(json.dumps(shuffle_read_modes(fault), indent=1))
        _print_fetch_summary()
        _print_telemetry()
        _dump_trace(trace_path)
        return
    if "--dynamic-shards" in sys.argv:
        fault = ""
        if "--fault" in sys.argv:  # e.g. --fault latency_ms=20,spikes=50
            fault = sys.argv[sys.argv.index("--fault") + 1]
        print(json.dumps(dynamic_shard_drain(fault), indent=1))
        _print_telemetry()
        _dump_trace(trace_path)
        return
    if "--dsserve" in sys.argv:
        fault = ""
        if "--fault" in sys.argv:  # e.g. --fault resets=2,seed=7
            fault = sys.argv[sys.argv.index("--fault") + 1]
        print(json.dumps(dsserve_drain(fault), indent=1))
        _print_telemetry()
        _dump_trace(trace_path)
        return
    import jax

    jax.local_devices()
    rng = np.random.default_rng(5)
    NB = 8060928
    bufs = [rng.integers(0, 255, NB, dtype=np.uint8) for _ in range(8)]
    big = rng.normal(size=1 << 20)  # ~8MB f64 for numpy work
    N = 20
    out = {}
    for r in range(2):
        out[f"none_{r}"] = put_loop(bufs, N)
        out[f"sleep5ms_{r}"] = put_loop(
            bufs, N, lambda: time.sleep(0.005)
        )
        out[f"pyspin5ms_{r}"] = put_loop(bufs, N, lambda: spin(0.005))
        out[f"numpy_copy_{r}"] = put_loop(
            bufs, N, lambda: numpy_work(big)
        )
        out[f"pyspin20ms_{r}"] = put_loop(bufs, N, lambda: spin(0.020))
    print(json.dumps(out, indent=1))
    _print_telemetry()
    _dump_trace(trace_path)


if __name__ == "__main__":
    main()
