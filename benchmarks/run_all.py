"""Benchmark runner for every BASELINE.json config.

Writes benchmarks/RESULTS.json and prints one line per config. The driver's
single-line metric stays in bench.py (north-star: HIGGS rows/sec into HBM);
this runner gives the per-config breakdown:

1. libsvm_parser_test: HIGGS-like file → RowBlockIter
2. csv_parser + libfm_parser → RowBlockIter
3. RecordIO pack/read roundtrip with ThreadedIter prefetch
4. InputSplit sharded read over local + s3:// (hermetic fake) URIs
5. dmlc-submit multi-worker rank/world env (local backend, real rendezvous)

Run: python benchmarks/run_all.py  [BENCH_ROWS=... scales the data]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", "100000"))
RESULTS = {}


def timed(fn):
    t0 = time.perf_counter()
    out = fn()
    return out, time.perf_counter() - t0


def gen_libsvm(path: str, rows: int, d: int = 28) -> None:
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for start in range(0, rows, 10000):
            n = min(10000, rows - start)
            vals = rng.normal(size=(n, d))
            f.write(
                "".join(
                    "%d %s\n"
                    % (
                        i % 2,
                        " ".join(f"{j}:{vals[i, j]:.6f}" for j in range(d)),
                    )
                    for i in range(n)
                )
            )


def bench_libsvm(tmp: str) -> None:
    from dmlc_core_tpu import data as D

    path = os.path.join(tmp, "higgs.libsvm")
    gen_libsvm(path, N_ROWS)
    it, dt = timed(lambda: D.create_row_block_iter(path, type="libsvm"))
    rows = sum(b.size for b in it)
    assert rows == N_ROWS
    RESULTS["libsvm_rowblockiter_rows_per_sec"] = round(rows / dt, 1)


def bench_csv_libfm(tmp: str) -> None:
    from dmlc_core_tpu import data as D

    rng = np.random.default_rng(1)
    csv = os.path.join(tmp, "t.csv")
    with open(csv, "w") as f:
        for start in range(0, N_ROWS, 10000):
            n = min(10000, N_ROWS - start)
            m = rng.normal(size=(n, 14))
            f.write(
                "".join(",".join(f"{v:.5f}" for v in row) + "\n" for row in m)
            )
    it, dt = timed(lambda: D.create_row_block_iter(csv, type="csv"))
    rows = sum(b.size for b in it)
    assert rows == N_ROWS
    RESULTS["csv_rowblockiter_rows_per_sec"] = round(rows / dt, 1)

    fm = os.path.join(tmp, "t.libfm")
    nfm = N_ROWS // 2
    with open(fm, "w") as f:
        for start in range(0, nfm, 10000):
            n = min(10000, nfm - start)
            vals = rng.normal(size=(n, 8))
            f.write(
                "".join(
                    "%d %s\n"
                    % (
                        i % 2,
                        " ".join(
                            f"{j % 4}:{j}:{vals[i, j]:.5f}" for j in range(8)
                        ),
                    )
                    for i in range(n)
                )
            )
    it, dt = timed(lambda: D.create_row_block_iter(fm, type="libfm"))
    rows = sum(b.size for b in it)
    assert rows == nfm
    RESULTS["libfm_rowblockiter_rows_per_sec"] = round(rows / dt, 1)


def bench_recordio(tmp: str) -> None:
    from dmlc_core_tpu.io import split as io_split
    from dmlc_core_tpu.io.recordio import RecordIOReader, RecordIOWriter
    from dmlc_core_tpu.io.stream import FileStream

    path = os.path.join(tmp, "data.rec")
    rng = np.random.default_rng(2)
    n_rec = max(N_ROWS // 10, 1000)
    payloads = [rng.bytes(512) for _ in range(200)]
    t0 = time.perf_counter()
    with FileStream(path, "w") as f:
        w = RecordIOWriter(f)
        for i in range(n_rec):
            w.write_record(payloads[i % 200])
    dt_w = time.perf_counter() - t0
    size = os.path.getsize(path)
    RESULTS["recordio_write_mb_per_sec"] = round(size / dt_w / 1e6, 1)

    t0 = time.perf_counter()
    with FileStream(path, "r") as f:
        r = RecordIOReader(f)
        count = sum(1 for _ in r)
    dt_r = time.perf_counter() - t0
    assert count == n_rec
    RESULTS["recordio_read_mb_per_sec"] = round(size / dt_r / 1e6, 1)

    # threaded-prefetch split read (the ThreadedIter pipeline)
    t0 = time.perf_counter()
    sp = io_split.create(path, 0, 1, type="recordio")
    count = sum(1 for _ in sp)
    sp.close()
    dt_s = time.perf_counter() - t0
    assert count == n_rec
    RESULTS["recordio_threaded_split_mb_per_sec"] = round(size / dt_s / 1e6, 1)


def bench_recordio_staged(tmp: str) -> None:
    """North star #2: rowrec RecordIO → fused ELL batches → device
    (mirrors bench.py run_epoch_rec at run_all scale)."""
    try:
        import jax  # noqa: F401
    except ImportError:
        return
    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.data.rowrec import write_rowrec
    from dmlc_core_tpu.io.stream import FileStream
    from dmlc_core_tpu.staging import (
        BatchSpec,
        StagingPipeline,
        drain_close,
        ell_batches,
    )

    rng = np.random.default_rng(3)
    n, k = max(N_ROWS // 2, 1000), 39
    offset = np.arange(n + 1, dtype=np.int64) * k
    blk = RowBlock(
        offset=offset,
        label=rng.integers(0, 2, n).astype(np.float32),
        index=rng.integers(0, 1 << 20, n * k).astype(np.uint32),
        value=rng.uniform(0, 1, n * k).astype(np.float32),
    )
    path = os.path.join(tmp, "criteo.rec")
    with FileStream(path, "w") as f:
        write_rowrec(f, [blk])
    spec = BatchSpec(
        batch_size=4096, layout="ell", max_nnz=k,
        value_dtype=np.dtype(np.float16),
    )
    # best of two epochs: the first pays XLA compilation + transfer
    # warmup and grossly understates steady-state (bench.py best_of)
    best = float("inf")
    for _ in range(2):
        stream = ell_batches(path, spec)
        # timer covers pipeline construction: its prefetch thread starts
        # parsing immediately, and at small scale that work could
        # otherwise finish before an after-construction t0
        t0 = time.perf_counter()
        pipe = StagingPipeline(stream, depth=2)
        for _ in pipe:
            pass
        dt = time.perf_counter() - t0
        assert pipe.rows_staged == n
        drain_close(pipe, stream)
        best = min(best, dt)
    RESULTS["recordio_staged_rows_per_sec"] = round(n / best, 1)
    RESULTS["recordio_staged_mb_per_sec"] = round(
        os.path.getsize(path) / best / 1e6, 1
    )


def bench_sharded_split(tmp: str) -> None:
    from dmlc_core_tpu.io import split as io_split

    path = os.path.join(tmp, "higgs.libsvm")  # reuse from bench_libsvm
    size = os.path.getsize(path)
    t0 = time.perf_counter()
    total = 0
    for rank in range(4):
        sp = io_split.create(path, rank, 4, type="text")
        total += sum(1 for _ in sp)
        sp.close()
    dt = time.perf_counter() - t0
    assert total == N_ROWS
    RESULTS["inputsplit_local_4shard_mb_per_sec"] = round(size / dt / 1e6, 1)

    # s3:// via the hermetic fake (signed, ranged)
    from test_cloudfs import FakeS3Handler, _Server
    from dmlc_core_tpu.io.cloudfs import reset_singletons

    FakeS3Handler.STORE = {"bkt/higgs.libsvm": open(path, "rb").read()}
    srv = _Server(FakeS3Handler)
    os.environ["S3_ENDPOINT"] = srv.url
    os.environ["AWS_ACCESS_KEY_ID"] = FakeS3Handler.ACCESS
    os.environ["AWS_SECRET_ACCESS_KEY"] = FakeS3Handler.SECRET
    reset_singletons()
    try:
        t0 = time.perf_counter()
        total = 0
        for rank in range(2):
            sp = io_split.create("s3://bkt/higgs.libsvm", rank, 2, type="text")
            total += sum(1 for _ in sp)
            sp.close()
        dt = time.perf_counter() - t0
        assert total == N_ROWS
        RESULTS["inputsplit_s3_2shard_mb_per_sec"] = round(size / dt / 1e6, 1)
    finally:
        reset_singletons()
        srv.stop()
        os.environ.pop("S3_ENDPOINT")


def bench_submit(tmp: str) -> None:
    worker = os.path.join(tmp, "worker.py")
    out = os.path.join(tmp, "rank")
    with open(worker, "w") as f:
        f.write(
            f"""
import os, sys
sys.path.insert(0, {REPO!r})
from dmlc_core_tpu.tracker.client import RabitWorker
w = RabitWorker()
rank = w.start()
open({out!r} + str(rank), "w").write(os.environ["DMLC_ROLE"])
w.shutdown()
"""
        )
    from dmlc_core_tpu.tracker import opts as tr_opts
    from dmlc_core_tpu.tracker.backends import get_backend

    t0 = time.perf_counter()
    args = tr_opts.get_opts(
        ["--cluster", "local", "--num-workers", "4",
         "--host-ip", "127.0.0.1", sys.executable, worker]
    )
    get_backend("local")(args)
    dt = time.perf_counter() - t0
    assert all(os.path.exists(out + str(r)) for r in range(4))
    RESULTS["dmlc_submit_local_4worker_secs"] = round(dt, 3)


def main() -> None:
    subprocess.run(
        ["make", "-C", os.path.join(REPO, "native")],
        check=False, capture_output=True,
    )
    with tempfile.TemporaryDirectory() as tmp:
        for fn in (
            bench_libsvm,
            bench_csv_libfm,
            bench_recordio,
            bench_recordio_staged,
            bench_sharded_split,
            bench_submit,
        ):
            fn(tmp)
    for k, v in RESULTS.items():
        print(f"{k}: {v:,}")
    with open(os.path.join(REPO, "benchmarks", "RESULTS.json"), "w") as f:
        json.dump(RESULTS, f, indent=2)


if __name__ == "__main__":
    main()
