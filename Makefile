# One-command CI gate (reference scripts/travis/travis_script.sh:19-67:
# lint + gtest + sanitizer + endian runs per commit, rebuilt here as a
# single `make check`). Every step exits nonzero on failure.
#
#   make check        full gate: syntax lint, optimized native build,
#                     pytest (incl. native-vs-Python differential fuzz,
#                     tests/test_native.py::test_fuzz_parity), ASan +
#                     TSan rebuilds with the native parity suites under
#                     the sanitizer runtime, optimized rebuild, and the
#                     single-chip + 8-device-mesh dryrun
#   make test         pytest only
#   make native       optimized native core only
#   make bench        the driver benchmark (real device)

PY ?= python
LIBASAN := $(shell gcc -print-file-name=libasan.so)
LIBTSAN := $(shell gcc -print-file-name=libtsan.so)
# the suites that exercise the native .so (what the sanitizers can see)
NATIVE_TESTS := tests/test_native.py tests/test_fused.py tests/test_rowrec.py tests/test_libfm_ell.py tests/test_libsvm_ell.py

.PHONY: check lint native test sanitizers dryrun bench clean

check: lint native test sanitizers dryrun
	@echo "== make check: ALL GATES PASSED =="

lint:
	$(PY) -m compileall -q dmlc_core_tpu tests benchmarks bench.py __graft_entry__.py
	$(PY) tools/lint.py

native:
	$(MAKE) -C native

test: native
	$(PY) -m pytest tests/ -q

sanitizers:
	$(MAKE) -C native asan
	LD_PRELOAD=$(LIBASAN) ASAN_OPTIONS=detect_leaks=0 \
		$(PY) -m pytest $(NATIVE_TESTS) -q -p no:cacheprovider -m "not jax"
	$(MAKE) -C native tsan
	LD_PRELOAD=$(LIBTSAN) TSAN_OPTIONS=report_bugs=1 \
		$(PY) -m pytest tests/test_native.py -q -p no:cacheprovider
	$(MAKE) -C native   # leave the optimized build behind, never a sanitizer one

dryrun: native
	$(PY) __graft_entry__.py

bench: native
	$(PY) bench.py

clean:
	$(MAKE) -C native clean
